"""Device-plane reconfigurable collectives: the NCCL-role component.

The reference's core data plane is abort/reconfigure-capable *device*
collectives (reference: torchft/process_group.py:780-891, ProcessGroupNCCL).
This module is the TPU-native equivalent: a :class:`ProcessGroupXLA` whose
cross-replica-group collectives execute **as XLA collectives on device** —
``lax.psum``-class reductions over a ``jax.sharding.Mesh`` with a
``"replica"`` axis — instead of host pickle-over-TCP
(:class:`torchft_tpu.process_group.ProcessGroupHost`, the Gloo-role host
plane).

Two operating modes, selected automatically at ``configure()``:

- **local**: one Python process owns every device of the quorum (a
  single-host multi-chip slice, the driver's virtual-CPU-device dryrun, the
  thread-per-replica test harness). Replica ``r``'s payload lives on lead
  device ``r``; an op rendezvouses all replicas' contributions — zero-copy,
  ``jax.make_array_from_single_device_arrays`` wraps the already-placed
  per-device shards — and one jitted reduction runs over the mesh. XLA
  lowers the reduction over the sharded axis to a cross-device all-reduce
  that rides ICI on real hardware.

- **distributed**: each replica group's lead process joins a
  ``jax.distributed`` world spanning the quorum (collectives ride ICI/DCN
  on TPU pods; the CPU test fabric uses XLA's Gloo-backed cross-host
  collectives). The coordinator address is rendezvoused through the same KV
  store the host plane uses, under a quorum-scoped prefix, so concurrent
  reconfigurations never collide. Reconfiguring tears the old world down
  (``jax.distributed.shutdown`` + backend clear) and initializes the new
  membership keyed by ``quorum_id``.

Reconfiguration semantics and their cost:

- The reference aborts and rebuilds one NCCL communicator while the rest of
  the process (CUDA context, model tensors) survives. XLA has no
  per-communicator world: in distributed mode the runtime world is global
  to the process, so ``configure()`` after a membership change
  **invalidates live device arrays** in that process. That is acceptable
  exactly where this PG sits: on a membership change the Manager re-stages
  state anyway (healing receives a checkpoint; survivors re-``device_put``
  onto the new mesh), and ``WorldSizeMode.FIXED_WITH_SPARES``
  (manager.py:364-374) keeps the world constant so steady-state failures
  need no re-init at all — dead spares contribute zeros, matching the
  reference's no-recompile design.
- In local mode reconfiguration is cheap: a new mesh over the surviving
  lead devices plus fresh jitted reductions.

Timeout→abort dispatch, error swallowing, and fault injection come from the
existing wrappers (ProcessGroupWrapper and friends, process_group.py) —
this class plugs into them unchanged. ``device_native = True`` tells the
Manager to keep payloads on device instead of staging to numpy.
"""

from __future__ import annotations

import logging
import os
import socket
import threading
import time
from typing import Any, Callable, Dict, List, Optional, Sequence, Tuple

import numpy as np

from torchft_tpu.coordination import KvClient
from torchft_tpu.process_group import ProcessGroup, ReduceOp
from torchft_tpu.work import DummyWork, Future, FutureWork, Work

logger = logging.getLogger(__name__)

__all__ = ["ProcessGroupXLA"]


_REDUCERS = {
    ReduceOp.SUM: lambda a: a.sum(axis=0),
    ReduceOp.AVG: lambda a: a.mean(axis=0).astype(a.dtype),
    ReduceOp.MAX: lambda a: a.max(axis=0),
    ReduceOp.MIN: lambda a: a.min(axis=0),
    ReduceOp.PRODUCT: lambda a: a.prod(axis=0),
}

# Peer-failure detection latency for the per-quorum jax.distributed world.
# jax.distributed.initialize's default is 100s — useless for per-step fault
# tolerance; the reference's NCCL plane detects via op timeout in seconds.
_HEARTBEAT_TIMEOUT_S = float(os.environ.get("TORCHFT_XLA_HEARTBEAT_SEC", 10.0))


def _join_distributed_world(
    coord: str,
    rank: int,
    world_size: int,
    timeout: float,
) -> None:
    """Join a per-quorum ``jax.distributed`` world with FT-grade options.

    Vanilla ``jax.distributed.initialize`` is unusable as a reconfigurable
    communicator on this toolchain (jax 0.9.0, measured in
    docs/operations.md):

    - ``shutdown()`` on a degraded world blocks in the cooperative shutdown
      barrier and then ``LOG(FATAL)``s the process;
    - the default 100s heartbeat hides peer death from the quorum layer;
    - overriding ``missed_heartbeat_callback`` is not viable: jaxlib's
      binding cannot convert the ``absl::Status`` argument (``std::bad_cast``
      → ``std::terminate``).

    Nor can a degraded world be abandoned silently: a released client's
    heartbeat/error-poll threads hold it alive internally, and the
    coordination service pushes a task-death error to every live poller
    ~heartbeat_timeout after a peer dies (measured: 11.0s at the 10s
    default; ``recoverable=True`` merely stretches it to ~25s). The
    consequence is a hard toolchain invariant this module is designed
    around (docs/operations.md): **membership can only shrink by process
    restart** — a member of a degraded distributed world always dies; the
    short heartbeat bounds *when*, and the supervising launcher restarting
    it into the next quorum is the recovery path (the reference's
    Baby-subprocess isolation inverted: the trainer process is the
    expendable child, the launcher is the parent). Healthy transitions
    (same membership re-keyed, grows, graceful leaves) reconfigure
    IN-PROCESS via the cooperative shutdown barrier, which succeeds
    precisely when everyone is alive to vote.

    The same ``jax._src.distributed.global_state`` fields are populated as
    ``initialize`` would, so backend creation picks up the world normally.
    """
    import jax
    from jax._src import distributed as _dist
    from jax._src.lib import _jax as _jaxlib

    state = _dist.global_state
    if state.client is not None:
        raise RuntimeError(
            "a jax.distributed world is already initialized; tear it down "
            "before joining a new quorum"
        )

    hb = max(1, int(_HEARTBEAT_TIMEOUT_S))
    # the cooperative-shutdown barrier wait: short, because on a degraded
    # world the barrier CANNOT succeed and its failure is process-fatal —
    # a small bound turns "die eventually" into "die promptly, restart"
    shutdown_to = min(max(1, int(timeout)), 10)
    if rank == 0:
        bind = "[::]:" + coord.rsplit(":", 1)[1]
        state.service = _jaxlib.get_distributed_runtime_service(
            bind, world_size, heartbeat_timeout=hb,
            shutdown_timeout=shutdown_to,
        )

    try:
        client = _jaxlib.get_distributed_runtime_client(
            coord, rank,
            init_timeout=max(1, int(timeout)),
            heartbeat_timeout=hb,
            shutdown_timeout=shutdown_to,
            shutdown_on_destruction=False,
            use_compression=True,
        )
        logger.info(
            "joining distributed world %s as %d/%d", coord, rank, world_size
        )
        client.connect()
    except Exception:
        # symmetric cleanup: a failed join must not strand rank 0's live
        # service in jax global state — the next configure() would skip
        # teardown (no world was built) and rebind over a service still
        # holding the port and its threads. NOTE: on this toolchain the
        # world-never-filled case is usually process-FATAL (client.h
        # terminates on the registration deadline) rather than a Python
        # exception — that death is the documented restart-on-shrink path;
        # this cleanup covers the join failures that do raise in-process
        # (client construction errors, toolchains where connect raises).
        if rank == 0 and state.service is not None:
            service, state.service = state.service, None
            t = threading.Thread(
                target=service.shutdown,
                daemon=True,
                name="pgxla_service_shutdown",
            )
            t.start()
            t.join(5.0)  # bounded, like _teardown_distributed_world's
        raise
    state.client = client
    state.process_id = rank
    state.num_processes = world_size
    state.coordinator_address = coord


def _lead_devices_local(world: int) -> List[Any]:
    """One lead device per replica from the local device pool."""
    import jax

    devices = jax.devices()
    if len(devices) < world:
        raise RuntimeError(
            f"ProcessGroupXLA(local) needs >= {world} devices, have "
            f"{len(devices)}; construct ProcessGroupXLA(mode='distributed') "
            "before any other JAX use in the process, or use the host plane"
        )
    per = len(devices) // world
    return [devices[r * per] for r in range(world)]


class _Mailbox:
    """Local-mode p2p handoff (one send/recv pairing)."""

    def __init__(self) -> None:
        self._cond = threading.Condition()
        self._payload: Optional[List[Any]] = None
        self._set = False
        self._error: Optional[Exception] = None
        self._closed = False

    def put(self, payload: List[Any]) -> bool:
        """Deposit; returns False when the receiver already gave up
        (closed) — the payload is dropped instead of pinned forever."""
        with self._cond:
            if self._closed:
                return False
            self._payload = payload
            self._set = True
            self._cond.notify_all()
        return True

    def close(self) -> None:
        """Receiver gave up (timeout/abort): a late put must drop its
        payload rather than park device arrays in an orphan mailbox that
        no future recv (the seq counter advanced) will ever read."""
        with self._cond:
            self._closed = True
            self._payload = None
            self._cond.notify_all()

    def fail(self, err: Exception) -> None:
        """abort() path: wake a blocked get() with the abort error instead of
        letting it run out its full timeout."""
        with self._cond:
            self._error = self._error or err
            self._cond.notify_all()

    def get(self, timeout: float) -> List[Any]:
        with self._cond:
            if not self._cond.wait_for(
                lambda: self._set or self._error is not None, timeout
            ):
                raise TimeoutError("p2p recv timed out")
            if self._set:
                return self._payload  # type: ignore[return-value]
            raise self._error  # type: ignore[misc]


class _OpSlot:
    """Local-mode rendezvous for one collective op across replica threads."""

    def __init__(self, world_size: int) -> None:
        self.world_size = world_size
        self.lock = threading.Lock()
        self.contributions: Dict[int, List[Any]] = {}
        self.futures: Dict[int, Future] = {}

    def deposit(self, rank: int, payload: List[Any]) -> Tuple[Future, bool]:
        """Returns (this rank's future, am_i_last)."""
        with self.lock:
            self.contributions[rank] = payload
            fut = self.futures.setdefault(rank, Future())
            last = len(self.contributions) == self.world_size
        return fut, last

    def resolve(self, per_rank: Dict[int, Any]) -> None:
        with self.lock:
            futs = {r: self.futures.setdefault(r, Future()) for r in per_rank}
        for r, fut in futs.items():
            try:
                fut.set_result(per_rank[r])
            except RuntimeError:
                pass

    def fail(self, err: Exception) -> None:
        with self.lock:
            futs = [
                self.futures.setdefault(r, Future())
                for r in range(self.world_size)
            ]
        for fut in futs:
            try:
                fut.set_exception(err)
            except RuntimeError:
                pass


class _XlaWorld:
    """One configure() generation: mesh, jit cache, op rendezvous state.

    In local mode the world is shared by every replica's PG instance (they
    live in one process); ops rendezvous contributions by per-kind sequence
    number — aligned SPMD call order across replicas is the collective
    contract, exactly as with NCCL. In distributed mode each process holds
    its own world object and ops involve only the local shard.
    """

    def __init__(
        self,
        mesh: Any,
        leads: List[Any],
        world_size: int,
        distributed: bool,
        quorum_id: int,
    ) -> None:
        self.mesh = mesh
        self.leads = leads
        self.world_size = world_size
        self.distributed = distributed
        self.quorum_id = quorum_id
        self.lock = threading.Lock()
        self.error: Optional[Exception] = None
        self.slots: Dict[Tuple[str, int], _OpSlot] = {}
        self.mailboxes: Dict[Tuple[str, int], _Mailbox] = {}
        self._jit_cache: Dict[Any, Callable] = {}

    # ---------------------------------------------------------------- jit
    def reduce_fn(self, op: ReduceOp) -> Callable:
        """Jitted leaf-list reduction over the ``replica`` axis, fully
        replicated output. One cache entry per op; XLA re-specializes per
        shape set automatically and lowers the sharded-axis reduction to a
        cross-device all-reduce."""
        import jax
        from jax.sharding import NamedSharding, PartitionSpec as P

        key = ("reduce", op)
        if key not in self._jit_cache:
            reducer = _REDUCERS[op]
            self._jit_cache[key] = jax.jit(
                lambda args: [reducer(a) for a in args],
                out_shardings=NamedSharding(self.mesh, P()),
            )
        return self._jit_cache[key]

    def replicate_fn(self) -> Callable:
        """Jitted identity resharding replica-sharded inputs to fully
        replicated — the allgather building block (XLA lowers the reshard to
        an all-gather over the mesh axis)."""
        import jax
        from jax.sharding import NamedSharding, PartitionSpec as P

        key = ("replicate",)
        if key not in self._jit_cache:
            self._jit_cache[key] = jax.jit(
                lambda args: list(args),
                out_shardings=NamedSharding(self.mesh, P()),
            )
        return self._jit_cache[key]

    # ------------------------------------------------------------- arrays
    def global_array(self, leaf_shards: Dict[int, Any], shape: Tuple[int, ...]):
        """Assemble a replica-sharded global array from per-rank shards
        (each already on its rank's lead device, with a leading length-1
        axis). Local mode supplies every rank; distributed mode only its
        own."""
        import jax
        from jax.sharding import NamedSharding, PartitionSpec as P

        sharding = NamedSharding(self.mesh, P("replica"))
        arrays = [leaf_shards[r] for r in sorted(leaf_shards)]
        return jax.make_array_from_single_device_arrays(
            (self.world_size, *shape), sharding, arrays
        )

    def place(self, rank: int, leaf: Any) -> Any:
        """Put ``leaf`` on rank's lead device with a leading length-1 axis
        (its shard of the global replica-sharded array)."""
        import jax
        import jax.numpy as jnp

        if not isinstance(leaf, jax.Array):
            leaf = jnp.asarray(leaf)
        return jax.device_put(leaf[None], self.leads[rank])

    def result_for(self, out: Any, rank: int) -> Any:
        """The single-device view of a fully-replicated result on rank's
        lead device."""
        dev = self.leads[rank]
        for s in out.addressable_shards:
            if s.device == dev:
                return s.data
        # distributed mode: only the local shard is addressable
        return out.addressable_shards[0].data

    # ----------------------------------------------------------- rendezvous
    def slot(self, kind: str, seq: int) -> _OpSlot:
        with self.lock:
            s = self.slots.get((kind, seq))
            if s is None:
                s = _OpSlot(self.world_size)
                self.slots[(kind, seq)] = s
        return s

    def gc_slot(self, kind: str, seq: int) -> None:
        with self.lock:
            self.slots.pop((kind, seq), None)

    def mailbox(self, kind: str, seq: int) -> _Mailbox:
        with self.lock:
            mb = self.mailboxes.get((kind, seq))
            if mb is None:
                mb = _Mailbox()
                self.mailboxes[(kind, seq)] = mb
        return mb

    def gc_mailbox(self, kind: str, seq: int) -> None:
        with self.lock:
            self.mailboxes.pop((kind, seq), None)


# Process-global local-mode world registry: every replica's PG in this
# process joins the same world per (store key, quorum id, world size).
_local_worlds: Dict[Tuple[str, int, int], _XlaWorld] = {}
_local_worlds_lock = threading.Lock()


class ProcessGroupXLA(ProcessGroup):
    """Reconfigurable device-plane PG (see module docstring).

    ``mode``: "auto" (default; local when this process holds enough devices,
    else distributed), "local", or "distributed".
    """

    device_native = True

    def __init__(self, timeout: "float | Any" = 60.0, mode: str = "auto") -> None:
        super().__init__()
        self.set_timeout(timeout)
        self._mode = mode
        self._world: Optional[_XlaWorld] = None
        self._rank = 0
        self._size = 1
        self._lock = threading.Lock()
        self._seq: Dict[str, int] = {}
        self._error: Optional[Exception] = None
        self._dispatch_q: Optional[Any] = None  # distributed-mode op stream
        self._device_world_epoch = 0
        # last successful configure args, kept for the intra-group degrade
        # path (prepare_shrink re-lands the same world coordinates)
        self._last_configure: Optional[Tuple[str, int, int, int]] = None

    @property
    def requires_sync_quorum(self) -> bool:
        """Always False since the prepare/commit configure split: the
        control-plane part of a reconfigure (quorum-scoped coordinator
        rendezvous through the KV store) runs on the quorum thread via
        ``prepare_configure``, and the only backend-touching piece — the
        jax world swap in distributed mode — is returned as a commit
        callable the Manager applies from the main thread at the next
        safe point. The Manager still honors True from third-party PGs
        without the split (the safety valve this property used to be)."""
        return False

    @property
    def device_world_epoch(self) -> int:
        """Bumped every time this PG rebuilds the jax backend (per-quorum
        distributed worlds tear down + rejoin; the first distributed join
        rebuilds a backend that predates the world). Arrays created before
        a bump stay READABLE (their buffers own a client reference) but
        cannot mix with new-world arrays inside one jitted computation —
        the Manager watches this and re-lands registered user state on the
        live backend at the next main-thread sync point."""
        with self._lock:
            return self._device_world_epoch

    def _distributed_work(self, fn: Callable[[], Any]) -> Work:
        """Distributed-mode op: dispatch + materialization on one worker
        thread per PG (preserving issue order, like a communication stream),
        each op bounded by the configured timeout with ``abort`` as the
        watchdog — the analog of the reference's NCCL
        ``_WorkAcceleratorTimeout`` (process_group.py:714-777). Without
        this, a peer wedged mid-collective would block the caller
        unboundedly at first materialization."""
        import queue as _queue

        fut: Future = Future()
        timeout = self._timeout

        def run() -> None:
            import jax

            from torchft_tpu.futures import context_timeout

            try:
                with context_timeout(self.abort, timeout):
                    out = fn()
                    jax.block_until_ready(out)
                fut.set_result(out)
            except Exception as e:  # noqa: BLE001
                try:
                    fut.set_exception(e)
                except RuntimeError:
                    pass

        # enqueue under the lock: abort() swaps _dispatch_q and posts the
        # shutdown sentinel under the same lock, so an op can never land
        # behind the sentinel and leave its future unresolved
        with self._lock:
            if self._error is not None:
                raise self._error
            if self._dispatch_q is None:
                q: "_queue.Queue" = _queue.Queue()
                self._dispatch_q = q

                def pump() -> None:
                    while True:
                        item = q.get()
                        if item is None:
                            return
                        item()

                threading.Thread(
                    target=pump, daemon=True, name="pgxla_dispatch"
                ).start()
            self._dispatch_q.put(run)
        return FutureWork(fut)

    # ------------------------------------------------------------ lifecycle
    def configure(self, store_addr, replica_rank, replica_world_size, quorum_id=0):
        commit = self.prepare_configure(
            store_addr, replica_rank, replica_world_size, quorum_id=quorum_id
        )
        if commit is not None:
            commit()

    def prepare_configure(
        self, store_addr, replica_rank, replica_world_size, quorum_id=0
    ) -> Optional[Callable[[], None]]:
        """Two-phase configure (see ProcessGroup.prepare_configure).

        Local mode never touches the process-global jax runtime, so the
        whole configure is prepare-safe and there is nothing to commit.
        Distributed mode stages the control plane here — the quorum-scoped
        coordinator rendezvous through the KV store, including the blocking
        wait for rank 0's address — and returns the backend swap (world
        teardown + ``jax.distributed`` rejoin + mesh build) as the commit,
        because ONLY the swap can race the trainer's own jax computations.
        """
        mode = self._mode
        if mode == "auto":
            # "auto" resolves to local: picking distributed here would
            # require counting local devices, and jax.devices() initializes
            # the XLA backend — after which jax.distributed.initialize is
            # forbidden. Distributed mode is therefore an explicit opt-in,
            # made before any other JAX use in the process (the launcher
            # knows the deployment shape; _lead_devices_local raises a
            # pointer here when local mode can't cover the world).
            mode = "local"

        with self._lock:
            self._last_configure = (
                store_addr, replica_rank, replica_world_size, quorum_id
            )

        if mode == "local":
            self._retire_current_world()
            world = self._configure_local(store_addr, replica_world_size, quorum_id)
            self._install_world(world, replica_rank, replica_world_size)
            return None

        coord = self._stage_distributed(store_addr, replica_rank, quorum_id)

        def commit() -> None:
            self._retire_current_world()
            world = self._configure_distributed(
                coord, replica_rank, replica_world_size, quorum_id
            )
            self._install_world(world, replica_rank, replica_world_size)

        return commit

    def prepare_shrink(
        self, dead_group_rank: int
    ) -> Optional[Callable[[], None]]:
        """Intra-group degrade path (docs/operations.md#degraded-replicas):
        a chip INSIDE this replica's group died and the group is shrinking
        its own TP/PP degree in place rather than leaving the quorum.

        The param movement is the reshard engine's job
        (torchft_tpu/parallel/degrade.py); this PG's job is to fence the
        collective generation the dead chip was entangled with. Local mode
        (one process owns the devices) returns a commit callable that
        poisons the current world — failing in-flight ops that could be
        waiting on the dead chip — and re-lands the same world coordinates
        on a fresh generation; co-resident replicas pick the rebuilt world
        up at their next configure, exactly like the poisoned-world rebuild
        on the ordinary reconfigure path. Distributed mode raises: a
        ``jax.distributed`` world's membership can only change by teardown
        + rejoin (a hard toolchain invariant), so an in-place shrink is the
        one reconfiguration this PG cannot stage — the Manager falls back
        to the classic leave-heal-rejoin path.
        """
        with self._lock:
            world = self._world
            args = self._last_configure
        if world is None or args is None:
            return None  # never configured: nothing is entangled yet
        if world.distributed:
            raise RuntimeError(
                "distributed-mode ProcessGroupXLA cannot shrink intra-group "
                "membership in place: jax.distributed world membership only "
                "changes by teardown + rejoin, so a chip loss inside the "
                "group takes the leave-heal-rejoin path"
            )
        store_addr, replica_rank, replica_world_size, quorum_id = args

        def commit() -> None:
            # poison-and-rebuild: retire fails the stale generation's
            # slots/mailboxes (ops entangled with the dead chip can never
            # complete), and _configure_local sees the poisoned registry
            # entry and builds a fresh world under the same key
            self._retire_current_world()
            w = self._configure_local(
                store_addr, replica_world_size, quorum_id
            )
            self._install_world(w, replica_rank, replica_world_size)

        return commit

    def _retire_current_world(self) -> None:
        with self._lock:
            old, self._world = self._world, None
            self._seq = {}  # fresh op ordering per generation
        if old is not None:
            if old.distributed:
                self._teardown_distributed_world()
            else:
                # Ops pending in the abandoned generation can never complete
                # (this member is leaving); fail them promptly instead of
                # letting co-resident replicas wait out their full timeouts
                # (ProcessGroupHost does the same via old.abort()).
                err = RuntimeError("process group torn down for reconfiguration")
                old.error = old.error or err
                with old.lock:
                    stale_slots = list(old.slots.values())
                    stale_mbs = list(old.mailboxes.values())
                for slot in stale_slots:
                    slot.fail(old.error)
                for mb in stale_mbs:
                    mb.fail(old.error)

    def _install_world(self, world: _XlaWorld, replica_rank, replica_world_size) -> None:
        with self._lock:
            self._world = world
            self._rank = replica_rank
            self._size = replica_world_size
            self._error = None  # errored state clears on reconfigure

    def _configure_local(self, store_addr, world_size, quorum_id) -> _XlaWorld:
        from jax.sharding import Mesh

        base = store_addr.split("/", 1)[0]  # the store's host:port
        key = (store_addr, quorum_id, world_size)
        with _local_worlds_lock:
            world = _local_worlds.get(key)
            if world is not None and world.error is not None:
                # a poisoned generation (aborted/torn down) must not be
                # handed back to a reconfiguring replica — build fresh
                world = None
            if world is None:
                leads = _lead_devices_local(world_size)
                mesh = Mesh(np.array(leads), ("replica",))
                world = _XlaWorld(
                    mesh, leads, world_size, distributed=False, quorum_id=quorum_id
                )
                # prune superseded generations of the same store (exact
                # host:port match — a prefix match would reap an unrelated
                # store like :50001 when pruning :5000)
                for k in [
                    k for k, w in _local_worlds.items()
                    if k[0].split("/", 1)[0] == base and k[1] < quorum_id
                ]:
                    del _local_worlds[k]
                _local_worlds[key] = world
        return world

    def _stage_distributed(self, store_addr, rank, quorum_id) -> str:
        """Control-plane half of a distributed reconfigure — safe on the
        quorum thread. Rank 0 publishes a coordinator address under the
        quorum-scoped KV prefix; everyone else blocks on the get until it
        lands. Pure KV RPCs: no jax state is touched."""
        host_port, _, path = store_addr.partition("/")
        prefix = f"{path or 'pgxla'}/{quorum_id}"
        kv = KvClient(host_port, connect_timeout=self._timeout)

        if rank == 0:
            coord = f"{_my_host()}:{_free_port()}"
            kv.set(f"{prefix}/xla_coordinator", coord, timeout=self._timeout)
        else:
            coord = kv.get(f"{prefix}/xla_coordinator", timeout=self._timeout).decode()
        return coord

    def _configure_distributed(
        self, coord, rank, world_size, quorum_id
    ) -> _XlaWorld:
        """Backend half of a distributed reconfigure: join the per-quorum
        ``jax.distributed`` world at the pre-rendezvoused coordinator and
        build the mesh. Runs at COMMIT time, on the Manager's main thread."""
        import jax
        from jax.sharding import Mesh

        _join_distributed_world(coord, rank, world_size, self._timeout)

        devices = jax.devices()
        if any(
            not any(d.process_index == p for d in devices)
            for p in range(world_size)
        ):
            # The local backend predates the distributed world: a trainer
            # whose main thread touched jax before its FIRST distributed
            # configure (computing grads while the async quorum runs) has
            # a cached single-process backend, so device discovery never
            # saw the world we just joined. Rebuild it — per-quorum
            # teardown does the same clear before every REjoin; arrays
            # created on the old backend stay readable (their buffers own
            # a client reference) and collectives device_put onto the new
            # world's mesh.
            jax.clear_caches()
            try:
                import jax.extend

                jax.extend.backend.clear_backends()
            except Exception as e:  # noqa: BLE001
                logger.warning("clear_backends failed: %s", e)
            with self._lock:
                self._device_world_epoch += 1
            devices = jax.devices()
        leads = []
        for p in range(world_size):
            pd = [d for d in devices if d.process_index == p]
            if not pd:
                raise RuntimeError(f"no devices visible for process {p}")
            leads.append(min(pd, key=lambda d: d.id))
        mesh = Mesh(np.array(leads), ("replica",))
        return _XlaWorld(
            mesh, leads, world_size, distributed=True, quorum_id=quorum_id
        )

    def _teardown_distributed_world(self) -> None:
        """Leave the per-quorum world.

        1. ``clear_backends`` first — the backend holds a reference to the
           runtime client; the client cannot be released while a backend
           could still issue RPCs through it.
        2. Cooperative ``client.shutdown()`` on a bounded daemon thread. On
           a HEALTHY transition (same members re-keyed, grow, graceful
           leave) the shutdown barrier completes in milliseconds, the
           client's heartbeat/error-poll threads stop, and the teardown is
           clean. On a DEGRADED world the barrier cannot complete and its
           failure (or the coordinator's task-death error push, whichever
           lands first) is process-fatal by toolchain design — the short
           ``shutdown_timeout``/heartbeat bounds make that death prompt,
           and the supervising launcher restarting this process into the
           next quorum is the recovery path (see _join_distributed_world's
           docstring and docs/operations.md). Merely dropping the reference
           is NOT an escape hatch: the client's own threads keep it alive
           and polling, and the poll fatals within a heartbeat window
           anyway.
        3. Rank 0 shuts the coordination service down after the barrier.
        """
        import jax
        from jax._src import distributed as _dist

        jax.clear_caches()
        try:
            import jax.extend

            jax.extend.backend.clear_backends()
        except Exception as e:  # noqa: BLE001
            logger.warning("clear_backends failed: %s", e)
        # the abort watchdog runs this teardown on a daemon thread while
        # the main thread may be reading device_world_epoch — a bare += 1
        # here can lose a bump and mask a backend rebuild from the Manager
        with self._lock:
            self._device_world_epoch += 1

        state = _dist.global_state
        client, state.client = state.client, None
        service, state.service = state.service, None
        state.process_id = 0
        state.num_processes = None
        state.coordinator_address = None

        if client is not None:
            t = threading.Thread(
                target=lambda: client.shutdown(),
                daemon=True,
                name="pgxla_client_shutdown",
            )
            t.start()
            t.join(12.0)
        del client
        if service is not None:
            t = threading.Thread(
                target=lambda: service.shutdown(),
                daemon=True,
                name="pgxla_service_shutdown",
            )
            t.start()
            t.join(5.0)

    def abort(self) -> None:
        err = RuntimeError("process group aborted")
        with self._lock:
            world, self._world = self._world, None
            self._error = self._error or err
            q, self._dispatch_q = self._dispatch_q, None
        if q is not None:
            q.put(None)  # stop the dispatch pump after draining queued ops
        if world is None:
            return
        world.error = world.error or err
        with world.lock:
            slots = list(world.slots.values())
            mailboxes = list(world.mailboxes.values())
        for slot in slots:
            slot.fail(world.error)
        for mb in mailboxes:
            mb.fail(world.error)
        if world.distributed:
            # The XLA analog of ncclCommAbort — except jax.distributed's
            # shutdown is graceful and can block behind a peer wedged in a
            # collective. abort() must return promptly (the Manager calls it
            # from timeout watchdogs), so the teardown runs on a daemon
            # thread with a bounded grace join. If the runtime stays wedged,
            # the supervising launcher restarts the process — the same
            # escalation path the reference's Baby-NCCL design exists for.
            t = threading.Thread(
                target=self._teardown_distributed_world,
                daemon=True,
                name="pgxla_abort_teardown",
            )
            t.start()
            t.join(5.0)

    def shutdown(self) -> None:
        self.abort()

    def errored(self) -> Optional[Exception]:
        with self._lock:
            if self._error is not None:
                return self._error
            world = self._world
        return None if world is None else world.error

    def size(self) -> int:
        return self._size

    def rank(self) -> int:
        return self._rank

    # ------------------------------------------------------------ internals
    def _require_world(self) -> _XlaWorld:
        with self._lock:
            world = self._world
        if world is None:
            raise RuntimeError("process group is not configured")
        if world.error is not None:
            raise world.error
        return world

    def _bump_seq(self, kind: str) -> int:
        with self._lock:
            n = self._seq.get(kind, 0)
            self._seq[kind] = n + 1
        return n

    def _deposit_checked(
        self,
        world: _XlaWorld,
        slot: _OpSlot,
        kind: str,
        seq: int,
        rank: int,
        leaves: List[Any],
    ) -> Tuple[Future, bool]:
        """Deposit, then close the register/abort race: abort() fails the
        slots it can see under world.lock, so a slot created (or deposited
        into) after that snapshot would hang its future to the wait timeout.
        world.error is set before the snapshot is taken — if it is not
        visible after our deposit, abort() will see our slot. Same shape as
        the ProcessGroupBaby._submit re-check."""
        fut, last = slot.deposit(rank, leaves)
        if world.error is not None:
            slot.fail(world.error)
            world.gc_slot(kind, seq)
            return fut, False
        return fut, last

    def _finish_local(
        self,
        world: _XlaWorld,
        slot: _OpSlot,
        kind: str,
        seq: int,
        compute: Callable[[Dict[int, List[Any]]], Dict[int, Any]],
    ) -> None:
        """Run ``compute`` over the full contribution set (last-arriving
        thread), resolving every rank's future."""
        try:
            slot.resolve(compute(slot.contributions))
        except Exception as e:  # noqa: BLE001
            world.error = world.error or e
            slot.fail(e)
        finally:
            world.gc_slot(kind, seq)

    def _run_reduce(
        self,
        world: _XlaWorld,
        op: ReduceOp,
        shards_by_rank: Dict[int, List[Any]],
        shapes: List[Tuple[int, ...]],
    ) -> List[Any]:
        per_leaf = [
            world.global_array(
                {r: shards_by_rank[r][i] for r in shards_by_rank}, shapes[i]
            )
            for i in range(len(shapes))
        ]
        return world.reduce_fn(op)(per_leaf)

    # ----------------------------------------------------------- collectives
    def allreduce(self, arrays: Sequence[Any], op: ReduceOp = ReduceOp.SUM) -> Work:
        world = self._require_world()
        rank = self._rank
        leaves = [world.place(rank, a) for a in arrays]
        shapes = [tuple(np.shape(a)) for a in arrays]

        if world.distributed:
            return self._distributed_work(
                lambda: [
                    world.result_for(o, rank)
                    for o in self._run_reduce(world, op, {rank: leaves}, shapes)
                ]
            )

        def compute(contribs: Dict[int, List[Any]]) -> Dict[int, Any]:
            outs = self._run_reduce(world, op, contribs, shapes)
            return {
                r: [world.result_for(o, r) for o in outs] for r in contribs
            }

        seq = self._bump_seq("allreduce")
        slot = world.slot("allreduce", seq)
        fut, last = self._deposit_checked(world, slot, "allreduce", seq, rank, leaves)
        if last:
            self._finish_local(world, slot, "allreduce", seq, compute)
        return FutureWork(fut)

    def allgather(self, arrays: Sequence[Any]) -> Work:
        """Resolves to ``[rank0's arrays, rank1's arrays, ...]``."""
        world = self._require_world()
        rank = self._rank
        leaves = [world.place(rank, a) for a in arrays]
        shapes = [tuple(np.shape(a)) for a in arrays]

        def rows_for(outs: List[Any], r: int) -> List[List[Any]]:
            mine = [world.result_for(o, r) for o in outs]  # each (W, *shape)
            return [
                [leaf[src] for leaf in mine] for src in range(world.world_size)
            ]

        if world.distributed:
            def gather() -> Any:
                per_leaf = [
                    world.global_array({rank: leaves[i]}, shapes[i])
                    for i in range(len(shapes))
                ]
                return rows_for(world.replicate_fn()(per_leaf), rank)

            return self._distributed_work(gather)

        def compute(contribs: Dict[int, List[Any]]) -> Dict[int, Any]:
            per_leaf = [
                world.global_array(
                    {r: contribs[r][i] for r in contribs}, shapes[i]
                )
                for i in range(len(shapes))
            ]
            outs = world.replicate_fn()(per_leaf)
            return {r: rows_for(outs, r) for r in contribs}

        seq = self._bump_seq("allgather")
        slot = world.slot("allgather", seq)
        fut, last = self._deposit_checked(world, slot, "allgather", seq, rank, leaves)
        if last:
            self._finish_local(world, slot, "allgather", seq, compute)
        return FutureWork(fut)

    def broadcast(self, arrays: Sequence[Any], root: int = 0) -> Work:
        """Root's arrays land on every rank. Moves only root's payload —
        1x N bytes to each receiver — not the W x N an allgather would."""
        world = self._require_world()
        rank = self._rank

        if world.distributed:
            shapes = [tuple(np.shape(a)) for a in arrays]
            leaves = [world.place(rank, a) for a in arrays]

            def bcast() -> Any:
                import jax
                from jax.sharding import NamedSharding, PartitionSpec as P

                per_leaf = [
                    world.global_array({rank: leaves[i]}, shapes[i])
                    for i in range(len(shapes))
                ]
                # a[root] on a replica-sharded array lowers to moving just
                # root's shard to every device
                key = ("bcast", root)
                if key not in world._jit_cache:
                    world._jit_cache[key] = jax.jit(
                        lambda args: [a[root] for a in args],
                        out_shardings=NamedSharding(world.mesh, P()),
                    )
                outs = world._jit_cache[key](per_leaf)
                return [world.result_for(o, rank) for o in outs]

            return self._distributed_work(bcast)

        # local mode: rendezvous (broadcast is still a collective — every
        # rank joins), then copy root's already-placed leaves out
        import jax

        payload = (
            [world.place(rank, a)[0] for a in arrays] if rank == root else []
        )

        def compute(contribs: Dict[int, List[Any]]) -> Dict[int, Any]:
            src = contribs[root]
            return {
                r: [jax.device_put(a, world.leads[r]) for a in src]
                for r in contribs
            }

        seq = self._bump_seq("broadcast")
        slot = world.slot("broadcast", seq)
        fut, last = self._deposit_checked(world, slot, "broadcast", seq, rank, payload)
        if last:
            self._finish_local(world, slot, "broadcast", seq, compute)
        return FutureWork(fut)

    def reduce_scatter(
        self, input_chunks: Sequence[Sequence[Any]], op: ReduceOp = ReduceOp.SUM
    ) -> Work:
        """``input_chunks[r]``: this rank's contribution destined for rank r;
        resolves to the reduced chunk this rank owns. One batched reduction
        over all destination chunks; XLA fuses them into one program."""
        world = self._require_world()
        rank = self._rank
        n_per_dest = len(input_chunks[0]) if input_chunks else 0
        flat_in = [a for chunk in input_chunks for a in chunk]
        leaves = [world.place(rank, a) for a in flat_in]
        shapes = [tuple(np.shape(a)) for a in flat_in]

        def chunk_of(outs: List[Any], r: int) -> List[Any]:
            mine = [world.result_for(o, r) for o in outs]
            return mine[r * n_per_dest:(r + 1) * n_per_dest]

        if world.distributed:
            return self._distributed_work(
                lambda: chunk_of(
                    self._run_reduce(world, op, {rank: leaves}, shapes), rank
                )
            )

        def compute(contribs: Dict[int, List[Any]]) -> Dict[int, Any]:
            outs = self._run_reduce(world, op, contribs, shapes)
            return {r: chunk_of(outs, r) for r in contribs}

        seq = self._bump_seq("reduce_scatter")
        slot = world.slot("reduce_scatter", seq)
        fut, last = self._deposit_checked(world, slot, "reduce_scatter", seq, rank, leaves)
        if last:
            self._finish_local(world, slot, "reduce_scatter", seq, compute)
        return FutureWork(fut)

    def alltoall(self, input_chunks: Sequence[Any]) -> Work:
        """``input_chunks[r]``: chunk destined for rank r; resolves to
        ``[chunk from rank 0, chunk from rank 1, ...]``."""
        world = self._require_world()
        rank = self._rank

        if world.distributed:
            work = self.allgather(input_chunks)
            fut = work.get_future().then(
                lambda f: [row[rank] for row in f.value()]
            )
            return FutureWork(fut)

        import jax

        leaves = [world.place(rank, a) for a in input_chunks]

        def compute(contribs: Dict[int, List[Any]]) -> Dict[int, Any]:
            # pure permutation: move each (1, *s) shard to its destination
            return {
                r: [
                    jax.device_put(contribs[src][r][0], world.leads[r])
                    for src in sorted(contribs)
                ]
                for r in contribs
            }

        seq = self._bump_seq("alltoall")
        slot = world.slot("alltoall", seq)
        fut, last = self._deposit_checked(world, slot, "alltoall", seq, rank, leaves)
        if last:
            self._finish_local(world, slot, "alltoall", seq, compute)
        return FutureWork(fut)

    # ------------------------------------------------------------------ p2p
    def send(self, arrays: Sequence[Any], dst: int, tag: int = 0) -> Work:
        world = self._require_world()
        if world.distributed:
            raise RuntimeError(
                "ProcessGroupXLA p2p send/recv is local-mode only; pairwise "
                "cross-host transfers belong to the checkpoint transports "
                "(HTTP/PG) or the host plane"
            )
        rank = self._rank
        kind = f"p2p_{rank}_{dst}_{tag}"
        seq = self._bump_seq(kind)
        payload = [world.place(rank, a)[0] for a in arrays]
        if not world.mailbox(kind, seq).put(payload):
            # receiver already timed out / aborted this pairing: free the
            # dict entry (payload was dropped by the closed mailbox)
            world.gc_mailbox(kind, seq)
        return DummyWork(None)

    def recv(self, src: int, tag: int = 0) -> Work:
        world = self._require_world()
        if world.distributed:
            raise RuntimeError(
                "ProcessGroupXLA p2p send/recv is local-mode only; pairwise "
                "cross-host transfers belong to the checkpoint transports "
                "(HTTP/PG) or the host plane"
            )
        rank = self._rank
        kind = f"p2p_{src}_{rank}_{tag}"
        seq = self._bump_seq(kind)
        mb = world.mailbox(kind, seq)
        fut: Future = Future()
        timeout = self._timeout

        def do_recv() -> None:
            import jax

            try:
                payload = mb.get(timeout)
                fut.set_result(
                    [jax.device_put(a, world.leads[rank]) for a in payload]
                )
                # consume-once on success: drop the mailbox and its
                # retained device arrays
                world.gc_mailbox(kind, seq)
            except Exception as e:  # noqa: BLE001
                try:
                    fut.set_exception(e)
                except RuntimeError:
                    pass
                # on timeout/abort, CLOSE but keep the dict entry: a late
                # sender must find the closed mailbox and drop its payload
                # (removing it here would let the sender re-create a fresh
                # orphan that pins device arrays until reconfigure)
                mb.close()

        threading.Thread(target=do_recv, daemon=True, name="pgxla_recv").start()
        return FutureWork(fut)


def _free_port() -> int:
    s = socket.socket()
    s.bind(("", 0))
    port = s.getsockname()[1]
    s.close()
    return port


def _my_host() -> str:
    return os.environ.get("TORCHFT_HOST", "127.0.0.1")
