# Typed surface of the ctypes-backed coordination layer. The implementation
# builds these classes around a native C++ library at import time, which type
# checkers cannot see through; this stub pins the public API instead.

from datetime import timedelta
from typing import Dict, List, Optional

_Timeout = float | timedelta

def ensure_native_built() -> str: ...

class QuorumMember:
    replica_id: str
    address: str
    store_address: str
    step: int
    world_size: int
    shrink_only: bool
    commit_failures: int
    data: str
    def __init__(
        self,
        replica_id: str,
        address: str = ...,
        store_address: str = ...,
        step: int = ...,
        world_size: int = ...,
        shrink_only: bool = ...,
        commit_failures: int = ...,
        data: str = ...,
    ) -> None: ...

class Quorum:
    quorum_id: int
    participants: List[QuorumMember]
    created_ms: int
    def __init__(
        self,
        quorum_id: int,
        participants: List[QuorumMember],
        created_ms: int = ...,
    ) -> None: ...

class QuorumResult:
    quorum_id: int
    replica_rank: int
    replica_world_size: int
    recover_src_manager_address: str
    recover_src_replica_rank: Optional[int]
    recover_dst_replica_ranks: List[int]
    store_address: str
    max_step: int
    max_replica_rank: Optional[int]
    max_world_size: int
    heal: bool
    commit_failures: int
    replica_ids: List[str]
    def __init__(
        self,
        quorum_id: int,
        replica_rank: int,
        replica_world_size: int,
        recover_src_manager_address: str,
        recover_src_replica_rank: Optional[int],
        recover_dst_replica_ranks: List[int],
        store_address: str,
        max_step: int,
        max_replica_rank: Optional[int],
        max_world_size: int,
        heal: bool,
        commit_failures: int = ...,
        replica_ids: List[str] = ...,
    ) -> None: ...

class LighthouseServer:
    def __init__(
        self,
        bind: str = ...,
        min_replicas: int = ...,
        join_timeout_ms: int = ...,
        quorum_tick_ms: int = ...,
        heartbeat_timeout_ms: int = ...,
        health: Optional[dict] = ...,
        history_path: str = ...,
    ) -> None: ...
    def address(self) -> str: ...
    @property
    def port(self) -> int: ...
    def shutdown(self) -> None: ...

class ManagerServer:
    def __init__(
        self,
        replica_id: str,
        lighthouse_addr: str,
        hostname: str = ...,
        bind: str = ...,
        store_addr: str = ...,
        world_size: int = ...,
        heartbeat_interval: _Timeout = ...,
        connect_timeout: _Timeout = ...,
        quorum_retries: int = ...,
    ) -> None: ...
    def address(self) -> str: ...
    @property
    def port(self) -> int: ...
    def publish_telemetry(self, telemetry: dict) -> None: ...
    def health(self) -> dict: ...
    def clock_skew(self) -> dict: ...
    def shutdown(self) -> None: ...

class KvStoreServer:
    def __init__(self, bind: str = ...) -> None: ...
    @property
    def port(self) -> int: ...
    def address(self) -> str: ...
    def shutdown(self) -> None: ...

class LighthouseClient:
    def __init__(self, addr: str, connect_timeout: _Timeout = ...) -> None: ...
    def quorum(
        self,
        replica_id: str,
        timeout: _Timeout,
        address: str = ...,
        store_address: str = ...,
        step: int = ...,
        world_size: int = ...,
        shrink_only: bool = ...,
        data: Optional[Dict] = ...,
        commit_failures: int = ...,
    ) -> Quorum: ...
    def heartbeat(
        self,
        replica_id: str,
        timeout: _Timeout = ...,
        telemetry: Optional[dict] = ...,
    ) -> dict: ...
    def status(self, timeout: _Timeout = ...) -> dict: ...
    def health(self, timeout: _Timeout = ...) -> dict: ...

class ManagerClient:
    def __init__(self, addr: str, connect_timeout: _Timeout = ...) -> None: ...
    def should_commit(
        self,
        group_rank: int,
        step: int,
        should_commit: bool,
        timeout: _Timeout,
    ) -> bool: ...
    def kill(self, msg: str = ..., timeout: _Timeout = ...) -> None: ...

class KvClient:
    def __init__(self, addr: str, connect_timeout: _Timeout = ...) -> None: ...
    def set(self, key: str, value: bytes | str, timeout: _Timeout = ...) -> None: ...
    def get(self, key: str, timeout: _Timeout = ..., wait: bool = ...) -> bytes: ...
    def add(self, key: str, amount: int, timeout: _Timeout = ...) -> int: ...
    def check(self, keys: List[str], timeout: _Timeout = ...) -> bool: ...
    def delete(self, key: str, timeout: _Timeout = ...) -> bool: ...
    def num_keys(self, timeout: _Timeout = ...) -> int: ...

def quorum_compute(state: dict, opts: dict) -> dict: ...
def compute_quorum_results(
    replica_id: str, group_rank: int, quorum: dict, init_sync: bool = ...
) -> QuorumResult: ...
def health_scores(windows: Dict[str, list], opts: dict) -> Dict[str, float]: ...
def health_replay(script: list, opts: dict) -> dict: ...
def history_replay(jsonl_text: str) -> dict: ...
