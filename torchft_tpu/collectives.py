"""Quantized collectives: fp8-compressed allreduce / reduce-scatter.

Algorithm mirror of the reference (torchft/collectives.py:159-415): quantize
to rowwise-scaled fp8, alltoall so each rank owns one chunk, dequantize +
reduce locally in f32, requantize, allgather the reduced chunks, dequantize.
SUM and AVG only. Cuts the replicated-dim wire traffic ~4x vs f32 — on a
TPU fleet this is DCN bandwidth between replica groups, usually the
scarcest link.

The pipeline runs on a worker thread (reference `_QuantizedOpFuture`,
collectives.py:139-156) and resolves a Work future with the reduced arrays.
"""

from __future__ import annotations

import threading
from typing import Any, List, Sequence

import numpy as np

from torchft_tpu.ops.quantization import (
    dequantize_fp8_rowwise,
    quantize_fp8_rowwise,
)
from torchft_tpu.process_group import ProcessGroup, ReduceOp
from torchft_tpu.work import Future, FutureWork, Work

__all__ = ["allreduce_quantized", "reduce_scatter_quantized"]

_ROW = 512


def _flatten(arrays: Sequence[Any]) -> tuple[np.ndarray, List[tuple], List[np.dtype]]:
    hosts = [np.asarray(a) for a in arrays]
    shapes = [h.shape for h in hosts]
    dtypes = [h.dtype for h in hosts]
    flat = (
        np.concatenate([h.astype(np.float32).reshape(-1) for h in hosts])
        if hosts
        else np.zeros(0, np.float32)
    )
    return flat, shapes, dtypes


def _unflatten(flat: np.ndarray, shapes, dtypes) -> List[np.ndarray]:
    out = []
    off = 0
    for shape, dtype in zip(shapes, dtypes):
        size = int(np.prod(shape)) if shape else 1
        out.append(flat[off : off + size].reshape(shape).astype(dtype))
        off += size
    return out


def _run_async(fn) -> Work:
    fut: Future[Any] = Future()

    def runner():
        try:
            fut.set_result(fn())
        except BaseException as e:  # noqa: BLE001
            try:
                fut.set_exception(e)
            except RuntimeError:
                pass

    threading.Thread(target=runner, daemon=True, name="torchft_quant_coll").start()
    return FutureWork(fut)


def _reduce_scatter_core(
    flat: np.ndarray, op: ReduceOp, pg: ProcessGroup, row: int
) -> tuple[np.ndarray, int]:
    """Shared pipeline: pad -> per-dest-chunk quantize -> alltoall -> f32
    accumulate (-> AVG). Returns (this rank's reduced f32 chunk, chunk size)."""
    world = pg.size()
    chunk = -(-flat.size // world)
    padded = np.zeros(chunk * world, np.float32)
    padded[: flat.size] = flat
    sends = []
    for r in range(world):
        q, scales, n = quantize_fp8_rowwise(padded[r * chunk : (r + 1) * chunk], row)
        sends.append((q, scales, n))
    recvd = pg.alltoall(sends).get_future().wait()
    acc = np.zeros(chunk, np.float64)
    for q, scales, n in recvd:
        acc[:n] += dequantize_fp8_rowwise(np.asarray(q), np.asarray(scales), n)
    if op == ReduceOp.AVG:
        acc /= world
    return acc.astype(np.float32), chunk


def allreduce_quantized(
    arrays: Sequence[Any], op: ReduceOp, pg: ProcessGroup, row: int = _ROW
) -> Work:
    """fp8-compressed allreduce over the PG. Returns Work resolving to the
    reduced arrays (same shapes/dtypes as inputs)."""
    if op not in (ReduceOp.SUM, ReduceOp.AVG):
        raise ValueError(f"allreduce_quantized supports SUM/AVG, got {op}")

    flat, shapes, dtypes = _flatten(arrays)

    def run() -> List[np.ndarray]:
        world = pg.size()
        if world <= 1:
            out = flat if op == ReduceOp.SUM else flat.copy()
            return _unflatten(out, shapes, dtypes)

        acc, chunk = _reduce_scatter_core(flat, op, pg, row)

        # requantize the reduced chunk and allgather
        q, scales, n = quantize_fp8_rowwise(acc, row)
        gathered = pg.allgather([(q, scales, n)]).get_future().wait()

        out = np.zeros(chunk * world, np.float32)
        for r in range(world):
            (qg, sg, ng) = gathered[r][0]
            out[r * chunk : r * chunk + ng] = dequantize_fp8_rowwise(
                np.asarray(qg), np.asarray(sg), ng
            )
        return _unflatten(out[: flat.size], shapes, dtypes)

    return _run_async(run)


def reduce_scatter_quantized(
    arrays: Sequence[Any], op: ReduceOp, pg: ProcessGroup, row: int = _ROW
) -> Work:
    """fp8-compressed reduce-scatter: future resolves to this rank's reduced
    flat chunk (f32) of the concatenated input."""
    if op not in (ReduceOp.SUM, ReduceOp.AVG):
        raise ValueError(f"reduce_scatter_quantized supports SUM/AVG, got {op}")

    flat, _, _ = _flatten(arrays)

    def run() -> np.ndarray:
        if pg.size() <= 1:
            return flat.copy()
        acc, _ = _reduce_scatter_core(flat, op, pg, row)
        return acc

    return _run_async(run)
