"""Quantized collectives: fp8-compressed allreduce / reduce-scatter.

Algorithm mirror of the reference (torchft/collectives.py:159-415): quantize
to rowwise-scaled fp8, alltoall so each rank owns one chunk, dequantize +
reduce locally in f32, requantize, allgather the reduced chunks, dequantize.
SUM and AVG only. Cuts the replicated-dim wire traffic ~4x vs f32 — on a
TPU fleet this is DCN bandwidth between replica groups, usually the
scarcest link.

Two quantization engines behind one wire format (uint8 fp8 payload + f32
row scales + element count):

- **device (Pallas)**: when every input leaf is a ``jax.Array``, the
  quantize / dequantize+reduce / requantize stages run as the fused Pallas
  kernels (ops/quantization.py) on the accelerator — the production path,
  matching the reference's Triton kernels (torchft/quantization.py:531-686
  called from collectives.py:297-415). Only the ~1 byte/element compressed
  payload crosses to the host for the wire, so D2H traffic drops ~4x too.
- **host (numpy)**: fallback for numpy inputs (and any mixed pytree).

The pipeline runs on a worker thread (reference `_QuantizedOpFuture`,
collectives.py:139-156) and resolves a Work future with the reduced arrays.
"""

from __future__ import annotations

import threading
from typing import Any, List, Sequence

import numpy as np

from torchft_tpu.ops.quantization import (
    dequantize_fp8_rowwise,
    fused_dequantize_fp8,
    fused_quantize_fp8,
    quantize_fp8_rowwise,
)
from torchft_tpu.process_group import ProcessGroup, ReduceOp
from torchft_tpu.work import Future, FutureWork, Work

__all__ = ["allreduce_quantized", "is_device_tree", "reduce_scatter_quantized"]

_ROW = 512


def _ceil_div(a: int, b: int) -> int:
    return -(-a // b)


def is_device_tree(arrays: Sequence[Any]) -> bool:
    """True iff every leaf is a single-device jax.Array.

    Mesh-sharded leaves (NamedSharding over >1 device — e.g. fsdp-sharded
    DiLoCo pseudogradients) must take the host path: the eager Pallas
    quantize calls have no SPMD partitioning rule, so running them on a
    sharded array would either fail to lower or force a full gather onto
    one device. The host path's np.asarray performs the same gather but
    into host RAM, where the wire needs the bytes anyway.
    """
    import jax

    return bool(arrays) and all(
        isinstance(a, jax.Array) and len(a.sharding.device_set) == 1
        for a in arrays
    )


def _flatten(arrays: Sequence[Any]) -> tuple[np.ndarray, List[tuple], List[np.dtype]]:
    hosts = [np.asarray(a) for a in arrays]
    shapes = [h.shape for h in hosts]
    dtypes = [h.dtype for h in hosts]
    flat = (
        np.concatenate([h.astype(np.float32).reshape(-1) for h in hosts])
        if hosts
        else np.zeros(0, np.float32)
    )
    return flat, shapes, dtypes


def _unflatten(flat: np.ndarray, shapes, dtypes) -> List[np.ndarray]:
    out = []
    off = 0
    for shape, dtype in zip(shapes, dtypes):
        size = int(np.prod(shape)) if shape else 1
        out.append(flat[off : off + size].reshape(shape).astype(dtype))
        off += size
    return out


def _run_async(fn) -> Work:
    fut: Future[Any] = Future()

    def runner():
        try:
            fut.set_result(fn())
        except BaseException as e:  # noqa: BLE001
            try:
                fut.set_exception(e)
            except RuntimeError:
                pass

    threading.Thread(target=runner, daemon=True, name="torchft_quant_coll").start()
    return FutureWork(fut)


def _flatten_jax(arrays: Sequence[Any]):
    import jax.numpy as jnp

    shapes = [a.shape for a in arrays]
    dtypes = [a.dtype for a in arrays]
    flat = jnp.concatenate([a.astype(jnp.float32).reshape(-1) for a in arrays])
    return flat, shapes, dtypes


def _unflatten_jax(flat, shapes, dtypes) -> List[Any]:
    out = []
    off = 0
    for shape, dtype in zip(shapes, dtypes):
        size = 1
        for s in shape:
            size *= s
        out.append(flat[off:off + size].reshape(shape).astype(dtype))
        off += size
    return out


def _wire_from_device(q, scales, n: int):
    """Device fp8 (rows, row) + scales (rows, 1) -> host wire tuple
    (uint8 payload, f32 scales, n). The only D2H transfer is the ~1
    byte/element compressed payload."""
    return (
        np.asarray(q).view(np.uint8),
        np.asarray(scales).reshape(-1),
        n,
    )


def _device_from_wire(tuples: List[tuple], row: int):
    """Stack same-shaped wire tuples, dequantize in ONE fused kernel call,
    return (world, chunk) f32 on device."""
    import jax.numpy as jnp

    from torchft_tpu.ops.quantization import _FP8

    world = len(tuples)
    qs = np.stack([np.asarray(t[0]).view(_FP8) for t in tuples])  # (w, rows, row)
    ss = np.stack([np.asarray(t[1]) for t in tuples])  # (w, rows)
    rows = qs.shape[1]
    deq = fused_dequantize_fp8(
        jnp.asarray(qs).reshape(world * rows, row),
        jnp.asarray(ss).reshape(world * rows, 1),
        world * rows * row,
        row,
    )
    return deq.reshape(world, rows * row)


def _reduce_scatter_core_device(flat, op: ReduceOp, pg: ProcessGroup, row: int):
    """Device-path pipeline: pad so chunks are whole fp8 rows, quantize the
    whole buffer in one Pallas call, slice per destination for the wire,
    then dequantize+reduce the received chunks on device."""
    import jax.numpy as jnp

    world = pg.size()
    chunk_rows = max(1, _ceil_div(_ceil_div(int(flat.size), world), row))
    chunk = chunk_rows * row
    padded = jnp.zeros((chunk * world,), jnp.float32).at[: flat.size].set(flat)
    q, scales, _ = fused_quantize_fp8(padded, row)  # (world*chunk_rows, row)
    sends = [
        _wire_from_device(
            q[r * chunk_rows:(r + 1) * chunk_rows],
            scales[r * chunk_rows:(r + 1) * chunk_rows],
            chunk,
        )
        for r in range(world)
    ]
    recvd = pg.alltoall(sends).get_future().wait()
    deq = _device_from_wire(list(recvd), row)  # (world, chunk) f32 on device
    acc = deq.sum(axis=0)
    if op == ReduceOp.AVG:
        acc = acc / world
    return acc, chunk, chunk_rows


def _allreduce_quantized_device(flat, shapes, dtypes, op, pg, row):
    import jax.numpy as jnp

    world = pg.size()
    acc, chunk, chunk_rows = _reduce_scatter_core_device(flat, op, pg, row)

    q, scales, _ = fused_quantize_fp8(acc, row)
    gathered = pg.allgather([_wire_from_device(q, scales, chunk)]) \
        .get_future().wait()
    deq = _device_from_wire([g[0] for g in gathered], row)  # (world, chunk)
    out = deq.reshape(world * chunk)[: flat.size]
    return _unflatten_jax(out, shapes, dtypes)


def _reduce_scatter_core(
    flat: np.ndarray, op: ReduceOp, pg: ProcessGroup, row: int
) -> tuple[np.ndarray, int]:
    """Shared pipeline: pad -> per-dest-chunk quantize -> alltoall -> f32
    accumulate (-> AVG). Returns (this rank's reduced f32 chunk, chunk size).

    Chunks are rounded up to whole fp8 rows — the SAME partitioning as the
    device (Pallas) path, so a quorum where some ranks quantize on device
    and others on host exchanges identically-aligned chunks."""
    world = pg.size()
    chunk = max(1, _ceil_div(_ceil_div(flat.size, world), row)) * row
    padded = np.zeros(chunk * world, np.float32)
    padded[: flat.size] = flat
    sends = []
    for r in range(world):
        q, scales, n = quantize_fp8_rowwise(padded[r * chunk : (r + 1) * chunk], row)
        sends.append((q, scales, n))
    recvd = pg.alltoall(sends).get_future().wait()
    acc = np.zeros(chunk, np.float64)
    for q, scales, n in recvd:
        acc[:n] += dequantize_fp8_rowwise(np.asarray(q), np.asarray(scales), n)
    if op == ReduceOp.AVG:
        acc /= world
    return acc.astype(np.float32), chunk


def allreduce_quantized(
    arrays: Sequence[Any], op: ReduceOp, pg: ProcessGroup, row: int = _ROW
) -> Work:
    """fp8-compressed allreduce over the PG. Returns Work resolving to the
    reduced arrays (same shapes/dtypes as inputs)."""
    if op not in (ReduceOp.SUM, ReduceOp.AVG):
        raise ValueError(f"allreduce_quantized supports SUM/AVG, got {op}")

    if is_device_tree(arrays):
        dflat, dshapes, ddtypes = _flatten_jax(arrays)

        def run_device() -> List[Any]:
            if pg.size() <= 1:
                return _unflatten_jax(dflat, dshapes, ddtypes)
            return _allreduce_quantized_device(
                dflat, dshapes, ddtypes, op, pg, row
            )

        return _run_async(run_device)

    flat, shapes, dtypes = _flatten(arrays)

    def run() -> List[np.ndarray]:
        world = pg.size()
        if world <= 1:
            out = flat if op == ReduceOp.SUM else flat.copy()
            return _unflatten(out, shapes, dtypes)

        acc, chunk = _reduce_scatter_core(flat, op, pg, row)

        # requantize the reduced chunk and allgather
        q, scales, n = quantize_fp8_rowwise(acc, row)
        gathered = pg.allgather([(q, scales, n)]).get_future().wait()

        out = np.zeros(chunk * world, np.float32)
        for r in range(world):
            (qg, sg, ng) = gathered[r][0]
            out[r * chunk : r * chunk + ng] = dequantize_fp8_rowwise(
                np.asarray(qg), np.asarray(sg), ng
            )
        return _unflatten(out[: flat.size], shapes, dtypes)

    return _run_async(run)


def reduce_scatter_quantized(
    arrays: Sequence[Any], op: ReduceOp, pg: ProcessGroup, row: int = _ROW
) -> Work:
    """fp8-compressed reduce-scatter: future resolves to this rank's reduced
    flat chunk (f32) of the concatenated input."""
    if op not in (ReduceOp.SUM, ReduceOp.AVG):
        raise ValueError(f"reduce_scatter_quantized supports SUM/AVG, got {op}")

    flat, _, _ = _flatten(arrays)

    def run() -> np.ndarray:
        if pg.size() <= 1:
            return flat.copy()
        acc, _ = _reduce_scatter_core(flat, op, pg, row)
        return acc

    return _run_async(run)

