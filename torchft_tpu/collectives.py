"""Quantized collectives: fp8-compressed allreduce / reduce-scatter.

Algorithm mirror of the reference (torchft/collectives.py:159-415): quantize
to rowwise-scaled fp8, alltoall so each rank owns one chunk, dequantize +
reduce locally in f32, requantize, allgather the reduced chunks, dequantize.
SUM and AVG only. Cuts the replicated-dim wire traffic ~4x vs f32 — on a
TPU fleet this is DCN bandwidth between replica groups, usually the
scarcest link.

Three quantization engines behind one wire format (uint8 fp8 payload + f32
row scales + element count):

- **device (Pallas)**: single-device ``jax.Array`` trees run the
  quantize / dequantize+reduce / requantize stages as the fused Pallas
  kernels (ops/quantization.py) on the accelerator — matching the
  reference's Triton kernels (torchft/quantization.py:531-686 called from
  collectives.py:297-415). Only the ~1 byte/element compressed payload
  crosses to the host for the wire, so D2H traffic drops ~4x too.
- **SPMD (shard_map + Pallas)**: mesh-sharded leaves (fsdp-sharded DiLoCo
  pseudogradients) quantize shard-locally — the Pallas kernel is
  shard_map'ed over each leaf's own mesh, so the full f32 buffer never
  leaves its sharding; the reduced result lands back on the same
  mesh/spec. A layout signature rides the wire so ranks with divergent
  shardings fail loudly instead of reducing misaligned chunks.
- **host (numpy)**: fallback for numpy inputs (and any mixed pytree).

The pipeline runs on a worker thread (reference `_QuantizedOpFuture`,
collectives.py:139-156) and resolves a Work future with the reduced arrays.
"""

from __future__ import annotations

import threading
from typing import Any, List, Sequence

import numpy as np

from torchft_tpu.ops.quantization import (
    compress_bucket,
    decompress_bucket,
    dequantize_fp8_rowwise,
    fused_dequantize_fp8,
    fused_quantize_fp8,
    quantize_fp8_rowwise,
)
from torchft_tpu.process_group import ProcessGroup, ReduceOp
from torchft_tpu.work import Future, FutureWork, Work

__all__ = [
    "allreduce_compressed",
    "allreduce_quantized",
    "is_device_tree",
    "reduce_scatter_quantized",
]

_ROW = 512


def _ceil_div(a: int, b: int) -> int:
    return -(-a // b)


def is_device_tree(arrays: Sequence[Any]) -> bool:
    """True iff every leaf is a jax.Array (any sharding).

    Single-device trees run the fused Pallas engine on the global flat
    buffer. Mesh-sharded leaves (NamedSharding over >1 device — e.g.
    fsdp-sharded DiLoCo pseudogradients) run the SPMD engine: the Pallas
    quantize kernel is shard_map'ed over each leaf's own mesh, so every
    device compresses its local shard in place and only the ~1
    byte/element fp8 payload ever crosses D2H (the reference keeps its
    fp8 pipeline on-accelerator the same way,
    torchft/quantization.py:531-686 via collectives.py:297-415). Leaves
    whose sharded dims don't divide evenly fall back to the host engine
    at call time (shard_map needs even shards).
    """
    import jax

    return bool(arrays) and all(isinstance(a, jax.Array) for a in arrays)


def _flatten(arrays: Sequence[Any]) -> tuple[np.ndarray, List[tuple], List[np.dtype]]:
    hosts = [np.asarray(a) for a in arrays]
    shapes = [h.shape for h in hosts]
    dtypes = [h.dtype for h in hosts]
    flat = (
        np.concatenate([h.astype(np.float32).reshape(-1) for h in hosts])
        if hosts
        else np.zeros(0, np.float32)
    )
    return flat, shapes, dtypes


def _unflatten(flat: np.ndarray, shapes, dtypes) -> List[np.ndarray]:
    out = []
    off = 0
    for shape, dtype in zip(shapes, dtypes):
        size = int(np.prod(shape)) if shape else 1
        out.append(flat[off : off + size].reshape(shape).astype(dtype))
        off += size
    return out


def _run_async(fn) -> Work:
    fut: Future[Any] = Future()

    def runner():
        try:
            fut.set_result(fn())
        except BaseException as e:  # noqa: BLE001
            try:
                fut.set_exception(e)
            except RuntimeError:
                pass

    threading.Thread(target=runner, daemon=True, name="torchft_quant_coll").start()
    return FutureWork(fut)


def _flatten_jax(arrays: Sequence[Any]):
    import jax.numpy as jnp

    shapes = [a.shape for a in arrays]
    dtypes = [a.dtype for a in arrays]
    flat = jnp.concatenate([a.astype(jnp.float32).reshape(-1) for a in arrays])
    return flat, shapes, dtypes


def _unflatten_jax(flat, shapes, dtypes) -> List[Any]:
    out = []
    off = 0
    for shape, dtype in zip(shapes, dtypes):
        size = 1
        for s in shape:
            size *= s
        out.append(flat[off:off + size].reshape(shape).astype(dtype))
        off += size
    return out


def _wire_from_device(q, scales, n: int):
    """Device fp8 (rows, row) + scales (rows, 1) -> host wire tuple
    (uint8 payload, f32 scales, n). The only D2H transfer is the ~1
    byte/element compressed payload."""
    return (
        np.asarray(q).view(np.uint8),
        np.asarray(scales).reshape(-1),
        n,
    )


def _device_from_wire(tuples: List[tuple], row: int):
    """Stack same-shaped wire tuples, dequantize in ONE fused kernel call,
    return (world, chunk) f32 on device."""
    import jax.numpy as jnp

    from torchft_tpu.ops.quantization import _FP8

    world = len(tuples)
    qs = np.stack([np.asarray(t[0]).view(_FP8) for t in tuples])  # (w, rows, row)
    ss = np.stack([np.asarray(t[1]) for t in tuples])  # (w, rows)
    rows = qs.shape[1]
    deq = fused_dequantize_fp8(
        jnp.asarray(qs).reshape(world * rows, row),
        jnp.asarray(ss).reshape(world * rows, 1),
        world * rows * row,
        row,
    )
    return deq.reshape(world, rows * row)


def _pack_wire_device(q, scales):
    """(rows, row) fp8 + (rows, 1) f32 scales -> ONE flat uint8 device
    array. For device-native PGs the compressed wire must be a single
    array (a jitted XLA collective cannot move host tuples) — and packing
    keeps the whole exchange on device: on hardware the alltoall of the
    ~1 byte/element payload rides ICI/DCN with zero host staging."""
    import jax
    import jax.numpy as jnp

    qb = jax.lax.bitcast_convert_type(q, jnp.uint8).reshape(-1)
    sb = jax.lax.bitcast_convert_type(
        scales.astype(jnp.float32), jnp.uint8
    ).reshape(-1)
    return jnp.concatenate([qb, sb])


def _unpack_dequant_device(bufs, rows: int, row: int):
    """Inverse of _pack_wire_device over a list of same-shape wires:
    dequantize all in ONE fused kernel call; returns (len(bufs), rows*row)
    f32 on device."""
    import jax
    import jax.numpy as jnp

    world = len(bufs)
    stacked = jnp.stack([jnp.asarray(b) for b in bufs])  # (w, nbytes) u8
    qb = stacked[:, : rows * row].reshape(world * rows, row)
    sb = stacked[:, rows * row:].reshape(world * rows, 1, 4)
    q = jax.lax.bitcast_convert_type(qb, jnp.float8_e4m3fn)
    s = jax.lax.bitcast_convert_type(sb, jnp.float32).reshape(world * rows, 1)
    deq = fused_dequantize_fp8(q, s, world * rows * row, row)
    return deq.reshape(world, rows * row)


def _reduce_scatter_core_device(flat, op: ReduceOp, pg: ProcessGroup, row: int):
    """Device-path pipeline: pad so chunks are whole fp8 rows, quantize the
    whole buffer in one Pallas call, slice per destination for the wire,
    then dequantize+reduce the received chunks on device.

    Wire format by PG plane: device-native PGs exchange packed uint8
    device arrays (the collective stays on device end to end); host PGs
    get the host tuple wire (uint8 payload, f32 scales, n)."""
    import jax.numpy as jnp

    world = pg.size()
    device_pg = bool(getattr(pg, "device_native", False))
    chunk_rows = max(1, _ceil_div(_ceil_div(int(flat.size), world), row))
    chunk = chunk_rows * row
    padded = jnp.zeros((chunk * world,), jnp.float32).at[: flat.size].set(flat)
    q, scales, _ = fused_quantize_fp8(padded, row)  # (world*chunk_rows, row)
    if device_pg:
        sends = [
            _pack_wire_device(
                q[r * chunk_rows:(r + 1) * chunk_rows],
                scales[r * chunk_rows:(r + 1) * chunk_rows],
            )
            for r in range(world)
        ]
        recvd = pg.alltoall(sends).get_future().wait()
        deq = _unpack_dequant_device(list(recvd), chunk_rows, row)
    else:
        sends = [
            _wire_from_device(
                q[r * chunk_rows:(r + 1) * chunk_rows],
                scales[r * chunk_rows:(r + 1) * chunk_rows],
                chunk,
            )
            for r in range(world)
        ]
        recvd = pg.alltoall(sends).get_future().wait()
        deq = _device_from_wire(list(recvd), row)  # (world, chunk) f32
    acc = deq.sum(axis=0)
    if op == ReduceOp.AVG:
        acc = acc / world
    return acc, chunk, chunk_rows


def _allreduce_quantized_device(flat, shapes, dtypes, op, pg, row):
    world = pg.size()
    device_pg = bool(getattr(pg, "device_native", False))
    acc, chunk, chunk_rows = _reduce_scatter_core_device(flat, op, pg, row)

    q, scales, _ = fused_quantize_fp8(acc, row)
    if device_pg:
        gathered = pg.allgather([_pack_wire_device(q, scales)]) \
            .get_future().wait()
        deq = _unpack_dequant_device([g[0] for g in gathered], chunk_rows, row)
    else:
        gathered = pg.allgather([_wire_from_device(q, scales, chunk)]) \
            .get_future().wait()
        deq = _device_from_wire([g[0] for g in gathered], row)  # (w, chunk)
    out = deq.reshape(world * chunk)[: flat.size]
    return _unflatten_jax(out, shapes, dtypes)


# ---------------------------------------------------------------------------
# SPMD engine: mesh-sharded leaves quantize shard-locally via shard_map
# ---------------------------------------------------------------------------
class _UnevenSharding(Exception):
    """Leaf's sharded dims don't divide evenly; caller falls back to host."""


def _sharded_axes(spec) -> tuple:
    """Flatten a PartitionSpec into the ordered tuple of mesh axis names it
    shards over (the rows-layout order of the wire)."""
    axes: List[Any] = []
    for part in spec:
        if part is None:
            continue
        if isinstance(part, tuple):
            axes.extend(part)
        else:
            axes.append(part)
    return tuple(axes)


def _leaf_plan(a, row: int):
    """Per-leaf wire plan: how this leaf's rows lay out on the wire.

    kind "sharded": quantized shard-locally (mesh-order row stacking);
    kind "single": quantized on the leaf's one device (or replicated).
    """
    import jax
    from jax.sharding import NamedSharding

    sh = a.sharding
    if isinstance(sh, NamedSharding):
        axes = _sharded_axes(sh.spec)
        n_shards = 1
        for ax in axes:
            n_shards *= sh.mesh.shape[ax]
        if n_shards > 1:
            try:
                # shard_shape raises when a sharded dim doesn't divide
                # evenly — exactly the shapes shard_map can't handle
                local_shape = sh.shard_shape(a.shape)
            except ValueError as e:
                raise _UnevenSharding(str(e)) from None
            local_n = 1
            for s in local_shape:
                local_n *= s
            local_rows = max(1, _ceil_div(local_n, row))
            return {
                "kind": "sharded",
                "sharding": sh,
                "axes": axes,
                "local_shape": local_shape,
                "local_n": local_n,
                "rows": local_rows * n_shards,
                "shape": a.shape,
                "dtype": a.dtype,
            }
    n = int(a.size)
    return {
        "kind": "single",
        "sharding": sh,
        "n": n,
        "rows": max(1, _ceil_div(n, row)),
        "shape": a.shape,
        "dtype": a.dtype,
    }


def _quantize_leaf(a, plan, row: int):
    """Quantize one leaf per its plan; returns host (uint8 rows, f32 scales).

    Sharded leaves never materialize off their mesh: shard_map runs the
    Pallas quantize kernel on each device's own shard, and the only D2H is
    np.asarray on the fp8 output."""
    from torchft_tpu.utils import import_shard_map
    shard_map = import_shard_map()
    from jax.sharding import PartitionSpec as P

    if plan["kind"] == "sharded":
        sh = plan["sharding"]
        axes = plan["axes"]

        def local(x):
            q, s, _ = fused_quantize_fp8(x.reshape(-1), row)
            return q, s

        q, s = shard_map(
            local,
            mesh=sh.mesh,
            in_specs=(sh.spec,),
            out_specs=(P(axes, None), P(axes, None)),
            check_vma=False,
        )(a)
    else:
        q, s, _ = fused_quantize_fp8(a.reshape(-1), row)
    return np.asarray(q).view(np.uint8), np.asarray(s).reshape(-1)


def _reconstruct_leaf(q_rows: np.ndarray, scales: np.ndarray, plan, row: int):
    """Inverse of _quantize_leaf: land the reduced fp8 rows back on the
    leaf's own mesh (sharded H2D of compressed bytes, then a shard-local
    Pallas dequantize into the original spec)."""
    import jax
    from torchft_tpu.utils import import_shard_map
    shard_map = import_shard_map()
    from jax.sharding import NamedSharding, PartitionSpec as P

    from torchft_tpu.ops.quantization import _FP8

    if plan["kind"] == "sharded":
        sh = plan["sharding"]
        axes = plan["axes"]
        rows_sharding = NamedSharding(sh.mesh, P(axes, None))
        dq = jax.device_put(q_rows.view(_FP8), rows_sharding)
        ds = jax.device_put(
            scales.reshape(-1, 1).astype(np.float32), rows_sharding
        )
        local_n, local_shape, dtype = (
            plan["local_n"], plan["local_shape"], plan["dtype"],
        )

        def local(qv, sv):
            flat = fused_dequantize_fp8(qv, sv, local_n, row)
            return flat.reshape(local_shape).astype(dtype)

        out = shard_map(
            local,
            mesh=sh.mesh,
            in_specs=(P(axes, None), P(axes, None)),
            out_specs=sh.spec,
            check_vma=False,
        )(dq, ds)
        # older JAX canonicalizes trailing-None specs on shard_map outputs
        # (P('x', None) -> P('x')); re-pin the caller's exact sharding so
        # the leaf round-trips ==-equal (no resharding: specs are equivalent)
        if out.sharding != sh:
            out = jax.device_put(out, sh)
        return out

    import jax.numpy as jnp

    flat = fused_dequantize_fp8(
        jnp.asarray(q_rows.view(_FP8)),
        jnp.asarray(scales.reshape(-1, 1).astype(np.float32)),
        plan["n"],
        row,
    )
    out = flat.reshape(plan["shape"]).astype(plan["dtype"])
    return jax.device_put(out, plan["sharding"])


def _allreduce_quantized_sharded(arrays, op: ReduceOp, pg: ProcessGroup,
                                 row: int, plans=None):
    """SPMD fp8 allreduce for trees with mesh-sharded leaves.

    Wire layout: per-leaf row blocks, each leaf's rows stacked in its
    mesh-iteration shard order. Every rank must hold identically-sharded
    leaves (the SPMD contract — same program, same meshes); the layout
    signature rides the wire so a divergent peer fails loudly instead of
    reducing misaligned chunks."""
    import zlib

    world = pg.size()
    if plans is None:
        plans = [_leaf_plan(a, row) for a in arrays]
    parts = [_quantize_leaf(a, p, row) for a, p in zip(arrays, plans)]
    Q = np.concatenate([q for q, _ in parts], axis=0)  # (total_rows, row) u8
    S = np.concatenate([s for _, s in parts])  # (total_rows,)
    total_rows = Q.shape[0]
    # The signature must pin the full element ordering, not just the row
    # counts: two shardings of the same leaf (e.g. P(('fsdp','tp'), None)
    # vs P('fsdp','tp') on a 2x2 mesh) produce identical row counts but
    # different shard-local flattening orders — equal-rows collisions
    # would reduce misaligned elements silently.
    sig = zlib.crc32(
        repr((row, world, [
            (p["kind"], p.get("axes"), tuple(p["shape"]),
             p.get("local_shape"), str(p["dtype"]), p["rows"])
            for p in plans
        ])).encode()
    )

    chunk_rows = _ceil_div(total_rows, world)
    pad_rows = chunk_rows * world - total_rows
    if pad_rows:
        Q = np.concatenate([Q, np.zeros((pad_rows, row), np.uint8)], axis=0)
        S = np.concatenate([S, np.ones(pad_rows, np.float32)])
    chunk = chunk_rows * row
    device_pg = bool(getattr(pg, "device_native", False))

    def _pack_host(q_rows: np.ndarray, s_rows: np.ndarray) -> np.ndarray:
        """Host-side packed wire (same layout as _pack_wire_device, sig
        appended as 4 LE bytes): a device-native PG's jitted collective
        moves single arrays, not host tuples."""
        return np.concatenate([
            q_rows.reshape(-1),
            s_rows.astype(np.float32).view(np.uint8).reshape(-1),
            np.frombuffer(
                int(sig).to_bytes(4, "little"), dtype=np.uint8
            ).copy(),
        ])

    def _unpack_host(buf, n_rows: int):
        """-> (q (rows,row) u8, scales (rows,) f32); verifies the sig."""
        host = np.asarray(buf).view(np.uint8).reshape(-1)
        got_sig = int.from_bytes(bytes(host[-4:]), "little")
        if got_sig != sig:
            raise RuntimeError(
                "quantized-allreduce wire layout mismatch: a peer sent "
                f"signature {got_sig} vs local {sig} — ranks must hold "
                "identically-sharded leaves (same meshes, specs, and leaf "
                "order)"
            )
        q_part = host[: n_rows * row].reshape(n_rows, row)
        s_part = host[n_rows * row:-4].view(np.float32).reshape(n_rows)
        return q_part, s_part

    if device_pg:
        sends = [
            _pack_host(Q[r * chunk_rows:(r + 1) * chunk_rows],
                       S[r * chunk_rows:(r + 1) * chunk_rows])
            for r in range(world)
        ]
        recvd_packed = list(pg.alltoall(sends).get_future().wait())
        recvd = [
            (*_unpack_host(b, chunk_rows), chunk) for b in recvd_packed
        ]
    else:
        sends = [
            (Q[r * chunk_rows:(r + 1) * chunk_rows],
             S[r * chunk_rows:(r + 1) * chunk_rows], chunk, sig)
            for r in range(world)
        ]
        recvd = list(pg.alltoall(sends).get_future().wait())
        for t in recvd:
            if len(t) != 4 or t[3] != sig:
                raise RuntimeError(
                    "quantized-allreduce wire layout mismatch: a peer sent "
                    f"signature {t[3] if len(t) == 4 else '<legacy 3-tuple>'} "
                    f"vs local {sig} — ranks must hold identically-sharded "
                    "leaves (same meshes, specs, and leaf order)"
                )

    # chunk-sized stages run on the default device via the fused kernels
    # (a chunk is 1/world of the compressed buffer — small next to the
    # sharded full buffer the SPMD stages above keep distributed)
    deq = _device_from_wire([t[:3] for t in recvd], row)  # (world, chunk)
    acc = deq.sum(axis=0)
    if op == ReduceOp.AVG:
        acc = acc / world
    q2, s2, _ = fused_quantize_fp8(acc, row)
    q2_host = np.asarray(q2).view(np.uint8)
    s2_host = np.asarray(s2).reshape(-1)
    if device_pg:
        gathered_packed = pg.allgather([_pack_host(q2_host, s2_host)]) \
            .get_future().wait()
        gathered_qs = [
            _unpack_host(g[0], chunk_rows) for g in gathered_packed
        ]
    else:
        gathered = pg.allgather([(q2_host, s2_host, chunk, sig)]) \
            .get_future().wait()
        for g in gathered:
            if len(g[0]) != 4 or g[0][3] != sig:
                raise RuntimeError(
                    "quantized-allreduce wire layout mismatch in allgather"
                )
        gathered_qs = [
            (np.asarray(g[0][0]).view(np.uint8), np.asarray(g[0][1]))
            for g in gathered
        ]

    Qr = np.concatenate([q for q, _ in gathered_qs], axis=0)[:total_rows]
    Sr = np.concatenate(
        [s.reshape(-1) for _, s in gathered_qs]
    )[:total_rows]

    out, off = [], 0
    for plan in plans:
        rows_l = plan["rows"]
        out.append(
            _reconstruct_leaf(Qr[off:off + rows_l], Sr[off:off + rows_l],
                              plan, row)
        )
        off += rows_l
    return out


def _has_multidevice_leaf(arrays: Sequence[Any]) -> bool:
    return any(len(a.sharding.device_set) > 1 for a in arrays)


def _reduce_scatter_core(
    flat: np.ndarray, op: ReduceOp, pg: ProcessGroup, row: int
) -> tuple[np.ndarray, int]:
    """Shared pipeline: pad -> per-dest-chunk quantize -> alltoall -> f32
    accumulate (-> AVG). Returns (this rank's reduced f32 chunk, chunk size).

    Chunks are rounded up to whole fp8 rows — the SAME partitioning as the
    device (Pallas) path, so a quorum where some ranks quantize on device
    and others on host exchanges identically-aligned chunks."""
    world = pg.size()
    chunk = max(1, _ceil_div(_ceil_div(flat.size, world), row)) * row
    padded = np.zeros(chunk * world, np.float32)
    padded[: flat.size] = flat
    sends = []
    for r in range(world):
        q, scales, n = quantize_fp8_rowwise(padded[r * chunk : (r + 1) * chunk], row)
        sends.append((q, scales, n))
    recvd = pg.alltoall(sends).get_future().wait()
    acc = np.zeros(chunk, np.float64)
    for q, scales, n in recvd:
        acc[:n] += dequantize_fp8_rowwise(np.asarray(q), np.asarray(scales), n)
    if op == ReduceOp.AVG:
        acc /= world
    return acc.astype(np.float32), chunk


def allreduce_quantized(
    arrays: Sequence[Any], op: ReduceOp, pg: ProcessGroup, row: int = _ROW
) -> Work:
    """fp8-compressed allreduce over the PG. Returns Work resolving to the
    reduced arrays (same shapes/dtypes as inputs)."""
    if op not in (ReduceOp.SUM, ReduceOp.AVG):
        raise ValueError(f"allreduce_quantized supports SUM/AVG, got {op}")

    if is_device_tree(arrays):
        if _has_multidevice_leaf(arrays):
            try:
                plans = [_leaf_plan(a, row) for a in arrays]
            except _UnevenSharding:
                plans = None  # host fallback below
            if plans is not None:
                leaves = list(arrays)

                def run_sharded() -> List[Any]:
                    if pg.size() <= 1:
                        return leaves
                    return _allreduce_quantized_sharded(
                        leaves, op, pg, row, plans
                    )

                return _run_async(run_sharded)
            # uneven shards: run the host engine but keep the return-type
            # contract — results land back on each input leaf's sharding
            # so callers never see the engine choice
            shardings = [a.sharding for a in arrays]
            hflat, hshapes, hdtypes = _flatten(arrays)

            def run_host_restore() -> List[Any]:
                import jax

                world = pg.size()
                if world <= 1:
                    outs = _unflatten(hflat, hshapes, hdtypes)
                else:
                    outs = _host_allreduce_pipeline(
                        hflat, hshapes, hdtypes, op, pg, row
                    )
                return [
                    jax.device_put(o, s) for o, s in zip(outs, shardings)
                ]

            return _run_async(run_host_restore)
        else:
            dflat, dshapes, ddtypes = _flatten_jax(arrays)

            def run_device() -> List[Any]:
                if pg.size() <= 1:
                    return _unflatten_jax(dflat, dshapes, ddtypes)
                return _allreduce_quantized_device(
                    dflat, dshapes, ddtypes, op, pg, row
                )

            return _run_async(run_device)

    flat, shapes, dtypes = _flatten(arrays)

    def run() -> List[np.ndarray]:
        if pg.size() <= 1:
            out = flat if op == ReduceOp.SUM else flat.copy()
            return _unflatten(out, shapes, dtypes)
        return _host_allreduce_pipeline(flat, shapes, dtypes, op, pg, row)

    return _run_async(run)


def allreduce_compressed(
    arrays: Sequence[Any],
    op: ReduceOp,
    pg: ProcessGroup,
    mode: str = "fp8",
    row: int = _ROW,
) -> Work:
    """Compressed allreduce through the PG's self-healing ring.

    Unlike :func:`allreduce_quantized` (alltoall + allgather, one codec
    boundary per destination chunk), this ships ONE CompressedWire per
    call straight into ``pg.allreduce`` — on ``ProcessGroupHost`` that is
    the compressed ring whose reduce step dequantizes → accumulates →
    requantizes per hop and which re-forms around a dead link
    mid-collective (``inject_link_fault`` / ``set_reroute_observer``).
    ``mode`` is ``"fp8"`` or ``"int8"``. The Manager's streaming pipeline
    uses the same wire per bucket; this is the direct, non-managed entry
    for tests and custom callers. Host (numpy) inputs only."""
    if op not in (ReduceOp.SUM, ReduceOp.AVG):
        raise ValueError(f"allreduce_compressed supports SUM/AVG, got {op}")
    flat, shapes, dtypes = _flatten(arrays)
    wire = compress_bucket(flat, mode, row=row)

    def run() -> List[np.ndarray]:
        if pg.size() <= 1:
            return _unflatten(flat.copy(), shapes, dtypes)
        out = pg.allreduce([wire], op).get_future().wait()
        return _unflatten(decompress_bucket(out[0]), shapes, dtypes)

    return _run_async(run)


def _host_allreduce_pipeline(flat, shapes, dtypes, op, pg, row):
    """Host-engine allreduce body: reduce-scatter, requantize, allgather."""
    world = pg.size()
    acc, chunk = _reduce_scatter_core(flat, op, pg, row)

    q, scales, n = quantize_fp8_rowwise(acc, row)
    gathered = pg.allgather([(q, scales, n)]).get_future().wait()

    out = np.zeros(chunk * world, np.float32)
    for r in range(world):
        (qg, sg, ng) = gathered[r][0]
        out[r * chunk : r * chunk + ng] = dequantize_fp8_rowwise(
            np.asarray(qg), np.asarray(sg), ng
        )
    return _unflatten(out[: flat.size], shapes, dtypes)


def reduce_scatter_quantized(
    arrays: Sequence[Any], op: ReduceOp, pg: ProcessGroup, row: int = _ROW
) -> Work:
    """fp8-compressed reduce-scatter: future resolves to this rank's reduced
    flat chunk (f32) of the concatenated input.

    Single-device jax trees run the fused Pallas engine (quantize, wire,
    dequantize+reduce all on-accelerator — the reference keeps its
    reduce-scatter on-GPU the same way, collectives.py:159-296) and the
    chunk comes back as a jax.Array; numpy and mesh-sharded inputs use
    the host engine (mesh-sharded only while fully addressable — the
    host flatten gathers, so multi-host shardings raise on the future;
    allreduce_quantized is the op with an SPMD engine). Both engines
    share the row-aligned chunk partition, so mixed quorums exchange
    identically-aligned chunks."""
    if op not in (ReduceOp.SUM, ReduceOp.AVG):
        raise ValueError(f"reduce_scatter_quantized supports SUM/AVG, got {op}")

    if is_device_tree(arrays) and not _has_multidevice_leaf(arrays):
        leaves = list(arrays)

        def run_device():
            # flatten inside the worker: cross-leaf device disagreement
            # (leaves committed to different devices) must resolve through
            # the Work future like every other error in this module
            dflat, _, _ = _flatten_jax(leaves)
            if pg.size() <= 1:
                return dflat
            acc, _chunk, _rows = _reduce_scatter_core_device(
                dflat, op, pg, row
            )
            return acc

        return _run_async(run_device)

    flat, _, _ = _flatten(arrays)

    def run() -> np.ndarray:
        if pg.size() <= 1:
            return flat.copy()
        acc, _ = _reduce_scatter_core(flat, op, pg, row)
        return acc

    return _run_async(run)

