#include "manager_server.h"

#include <unistd.h>

#include <cstdio>
#include <cstdlib>

namespace tft {

namespace {
void log_info(const std::string& rid, const std::string& msg) {
  std::fprintf(stderr, "[manager %s] %s\n", rid.c_str(), msg.c_str());
}
}  // namespace

ManagerServer::ManagerServer(ManagerOpts opts) : opts_(std::move(opts)) {
  heartbeat_client_ = std::make_unique<RpcClient>(
      opts_.lighthouse_addr, Millis(opts_.connect_timeout_ms));
  quorum_client_ = std::make_unique<RpcClient>(
      opts_.lighthouse_addr, Millis(opts_.connect_timeout_ms));
  if (!opts_.aggregator_addr.empty()) adopt_aggregator(opts_.aggregator_addr);
  server_ = std::make_unique<RpcServer>(
      opts_.bind, [this](const std::string& m, const Json& p, TimePoint d) {
        return handle(m, p, d);
      });
  heartbeat_thread_ = std::thread([this] { heartbeat_loop(); });
}

ManagerServer::~ManagerServer() { shutdown(); }

std::string ManagerServer::address() const {
  std::string host = opts_.hostname.empty() ? local_hostname() : opts_.hostname;
  return host + ":" + std::to_string(server_->port());
}

void ManagerServer::shutdown() {
  bool was = running_.exchange(false);
  if (!was) return;
  quorum_cv_.notify_all();
  commit_cv_.notify_all();
  if (heartbeat_thread_.joinable()) heartbeat_thread_.join();
  std::vector<std::unique_ptr<WorkerSlot>> workers;
  {
    std::lock_guard<std::mutex> lk(mu_);
    workers.swap(quorum_workers_);
  }
  for (auto& w : workers)
    if (w->thread.joinable()) w->thread.join();
  server_->shutdown();
}

void ManagerServer::publish_telemetry(const std::string& telemetry_json) {
  Json t = Json::parse(telemetry_json);
  std::lock_guard<std::mutex> lk(telemetry_mu_);
  telemetry_ = std::move(t);
}

std::string ManagerServer::health_json() const {
  std::lock_guard<std::mutex> lk(telemetry_mu_);
  return last_health_.empty() ? "{}" : last_health_;
}

std::string ManagerServer::policy_json() const {
  std::lock_guard<std::mutex> lk(telemetry_mu_);
  return last_policy_.empty() ? "{}" : last_policy_;
}

std::string ManagerServer::clock_skew_json() const {
  std::lock_guard<std::mutex> lk(telemetry_mu_);
  Json j = Json::object();
  j["skew_ms"] = best_skew_ms_;
  j["rtt_ms"] = best_rtt_ms_;
  j["last_skew_ms"] = last_skew_ms_;
  j["last_rtt_ms"] = last_rtt_ms_;
  j["samples"] = skew_samples_;
  return j.dump();
}

std::shared_ptr<RpcClient> ManagerServer::agg_client(bool for_quorum) const {
  std::lock_guard<std::mutex> lk(agg_mu_);
  return for_quorum ? agg_quorum_client_ : agg_heartbeat_client_;
}

void ManagerServer::adopt_aggregator(const std::string& addr) {
  std::lock_guard<std::mutex> lk(agg_mu_);
  if (addr == agg_addr_ && agg_heartbeat_client_ && !agg_down_.load()) return;
  agg_addr_ = addr;
  // Separate beat/quorum clients for the same reason as the root pair.
  // Short connect timeout: connect_with_retry keeps retrying a refused
  // connection until its deadline, so a DEAD aggregator would otherwise
  // burn the full connect budget (default 10s) before failing over —
  // starving beats past the lighthouse expiry and eating the quorum
  // round's deadline. 1s bounds the failover cost; a live aggregator
  // connects instantly and blocking quorum waits are unaffected.
  Millis agg_connect(std::min<int64_t>(opts_.connect_timeout_ms, 1000));
  agg_heartbeat_client_ = std::make_shared<RpcClient>(addr, agg_connect);
  agg_quorum_client_ = std::make_shared<RpcClient>(addr, agg_connect);
  agg_down_.store(false);
}

std::string ManagerServer::control_status_json() const {
  std::lock_guard<std::mutex> lk(agg_mu_);
  bool configured = !agg_addr_.empty();
  bool via_agg = configured && !agg_down_.load();
  Json j = Json::object();
  j["aggregator_addr"] = agg_addr_;
  j["via_aggregator"] = via_agg;
  j["direct_mode"] = !via_agg;
  j["failovers"] = agg_failovers_.load();
  return j.dump();
}

void ManagerServer::heartbeat_loop() {
  while (running_.load()) {
    try {
      Json params = Json::object();
      params["replica_id"] = opts_.replica_id;
      {
        std::lock_guard<std::mutex> lk(telemetry_mu_);
        if (!telemetry_.is_null()) params["telemetry"] = telemetry_;
      }
      // Short per-beat timeout: the loop is serial, so one RPC stalling for
      // the full connect timeout (default 10s) would starve the beat past
      // the lighthouse's 5s expiry and get a LIVE replica evicted. 2s keeps
      // several retries inside the expiry window.
      int64_t beat_ms = std::min<int64_t>(opts_.connect_timeout_ms, 2000);
      bool sent = false;
      std::shared_ptr<RpcClient> agg =
          agg_down_.load() ? nullptr : agg_client(false);
      if (agg) {
        try {
          Json resp = agg->call("heartbeat", params, Millis(beat_ms));
          if (resp.contains("health")) {
            std::lock_guard<std::mutex> lk(telemetry_mu_);
            last_health_ = resp.get("health").dump();
          }
          if (resp.contains("policy")) {
            std::lock_guard<std::mutex> lk(telemetry_mu_);
            last_policy_ = resp.get("policy").dump();
          }
          // No skew update: the aggregator answers with ITS clock, not the
          // root lighthouse's — mixing the two would corrupt the estimate.
          sent = true;
        } catch (const std::exception& e) {
          agg_down_.store(true);
          agg_failovers_.fetch_add(1);
          log_info(opts_.replica_id,
                   std::string("aggregator beat failed, failing over to "
                               "direct lighthouse: ") +
                       e.what());
        }
      }
      if (!sent) {
        // Direct-to-root beat. While configured for an aggregator, ask the
        // root to name a (replacement) aggregator so the pod can re-form;
        // a flat fleet sends exactly the pre-aggregator frame.
        {
          std::lock_guard<std::mutex> lk(agg_mu_);
          if (!agg_addr_.empty()) params["want_aggregator"] = true;
        }
        int64_t t0 = epoch_millis_now();
        Json resp = heartbeat_client_->call("heartbeat", params, Millis(beat_ms));
        int64_t t1 = epoch_millis_now();
        if (resp.contains("health")) {
          std::lock_guard<std::mutex> lk(telemetry_mu_);
          last_health_ = resp.get("health").dump();
        }
        if (resp.contains("policy")) {
          std::lock_guard<std::mutex> lk(telemetry_mu_);
          last_policy_ = resp.get("policy").dump();
        }
        // Skew vs the lighthouse: the round-trip midpoint against server_ms.
        // Sign convention is replica-minus-lighthouse (positive when THIS
        // clock runs ahead) — the trace merger subtracts skew_ms to move
        // replica timestamps onto the lighthouse's clock. Keep the
        // minimum-RTT sample's estimate — its midpoint assumption
        // (symmetric path) has the least queueing error (NTP's rule).
        if (resp.contains("server_ms")) {
          double server_ms =
              static_cast<double>(resp.get("server_ms").as_int());
          double rtt = static_cast<double>(t1 - t0);
          double skew = (static_cast<double>(t0 + t1) / 2.0) - server_ms;
          std::lock_guard<std::mutex> lk(telemetry_mu_);
          skew_samples_ += 1;
          last_rtt_ms_ = rtt;
          last_skew_ms_ = skew;
          if (skew_samples_ == 1 || rtt <= best_rtt_ms_) {
            best_rtt_ms_ = rtt;
            best_skew_ms_ = skew;
          }
        }
        if (resp.contains("aggregator")) {
          std::string replacement = resp.get("aggregator").as_string();
          log_info(opts_.replica_id,
                   "root named aggregator " + replacement + ", re-pointing");
          adopt_aggregator(replacement);
        }
      }
    } catch (const std::exception& e) {
      log_info(opts_.replica_id,
               std::string("failed to send heartbeat to lighthouse: ") + e.what());
    }
    // Sleep in small increments so shutdown() is prompt.
    int64_t remaining = opts_.heartbeat_interval_ms;
    while (remaining > 0 && running_.load()) {
      int64_t step = std::min<int64_t>(remaining, 50);
      std::this_thread::sleep_for(Millis(step));
      remaining -= step;
    }
  }
}

Json ManagerServer::handle(const std::string& method, const Json& params,
                           TimePoint deadline) {
  if (method == "quorum") return rpc_quorum(params, deadline);
  if (method == "checkpoint_metadata") return rpc_checkpoint_metadata(params);
  if (method == "should_commit") return rpc_should_commit(params, deadline);
  if (method == "kill") {
    std::string msg = params.get_or("msg", Json("")).as_string();
    std::fprintf(stderr, "[manager %s] got kill request: %s\n",
                 opts_.replica_id.c_str(), msg.c_str());
    std::fflush(stderr);
    _exit(1);
  }
  throw RpcError("invalid", "unknown manager method: " + method);
}

void ManagerServer::run_lighthouse_quorum(QuorumMember member, Millis timeout) {
  log_info(opts_.replica_id, "All workers joined - starting quorum");
  Json params = Json::object();
  params["requester"] = member.to_json();

  std::string last_err;
  int64_t retries = std::max<int64_t>(opts_.quorum_retries, 0);
  for (int64_t attempt = 0; attempt <= retries; ++attempt) {
    try {
      Json resp;
      bool got_resp = false;
      TimePoint attempt_deadline = Clock::now() + timeout;
      std::shared_ptr<RpcClient> agg =
          agg_down_.load() ? nullptr : agg_client(true);
      if (agg) {
        try {
          resp = agg->call("quorum", params, timeout);
          got_resp = true;
        } catch (const std::exception& e) {
          // Aggregator died mid-round: fail over to the root with the
          // budget that's left so this quorum round is not lost. A dead
          // aggregator fails fast (connection refused / broken pipe),
          // leaving nearly the full budget.
          agg_down_.store(true);
          agg_failovers_.fetch_add(1);
          log_info(opts_.replica_id,
                   std::string("aggregator quorum failed, failing over to "
                               "direct lighthouse: ") +
                       e.what());
        }
      }
      if (!got_resp) {
        Millis remaining(std::max<int64_t>(ms_until(attempt_deadline), 1));
        resp = quorum_client_->call("quorum", params, remaining);
      }
      QuorumSnapshot q = QuorumSnapshot::from_json(resp.get("quorum"));
      std::lock_guard<std::mutex> lk(mu_);
      latest_quorum_ = q;
      quorum_error_.clear();
      quorum_gen_ += 1;
      quorum_cv_.notify_all();
      return;
    } catch (const std::exception& e) {
      last_err = e.what();
      log_info(opts_.replica_id,
               "lighthouse quorum failed (attempt " + std::to_string(attempt) +
                   "): " + last_err);
      int64_t sleep_ms = std::max<int64_t>(
          100, std::chrono::duration_cast<Millis>(timeout).count() /
                   std::max<int64_t>(retries + 1, 1));
      if (attempt < retries) std::this_thread::sleep_for(Millis(sleep_ms));
    }
  }
  // Unlike the reference (which leaves waiters hanging on lighthouse failure,
  // a known TODO at src/manager.rs:229), broadcast the error so every rank's
  // quorum call fails fast instead of timing out.
  std::lock_guard<std::mutex> lk(mu_);
  quorum_error_ = "lighthouse quorum failed after " +
                  std::to_string(retries) + " retries: " + last_err;
  quorum_gen_ += 1;
  quorum_cv_.notify_all();
}

Json ManagerServer::rpc_quorum(const Json& params, TimePoint deadline) {
  int64_t group_rank = params.get("group_rank").as_int();
  int64_t step = params.get_or("step", Json(int64_t{0})).as_int();
  bool init_sync = params.get_or("init_sync", Json(true)).as_bool();

  log_info(opts_.replica_id,
           "Start quorum for group_rank " + std::to_string(group_rank));

  uint64_t waiting_gen;
  {
    std::unique_lock<std::mutex> lk(mu_);
    checkpoint_metadata_[group_rank] =
        params.get_or("checkpoint_metadata", Json("")).as_string();

    QuorumMember member;
    member.replica_id = opts_.replica_id;
    member.address = address();
    member.store_address = opts_.store_addr;
    member.step = step;
    member.world_size = opts_.world_size;
    member.shrink_only = params.get_or("shrink_only", Json(false)).as_bool();
    member.commit_failures =
        params.get_or("commit_failures", Json(int64_t{0})).as_int();
    member.data = params.get_or("data", Json("")).as_string();

    participants_[group_rank] = member;
    waiting_gen = quorum_gen_;

    if (static_cast<int64_t>(participants_.size()) == opts_.world_size &&
        running_.load()) {
      // Aggregate the replica's member across ALL group ranks before
      // forwarding: the last joiner's view alone would drop another rank's
      // commit_failures (no quorum bump -> poisoned communicator reused)
      // or shrink_only request, and overstate step if ranks disagree.
      QuorumMember agg = member;
      agg.data.clear();
      for (const auto& [r, m] : participants_) {  // std::map: rank order
        agg.step = std::min(agg.step, m.step);
        agg.commit_failures = std::max(agg.commit_failures, m.commit_failures);
        agg.shrink_only = agg.shrink_only || m.shrink_only;
        // deterministic: the lowest rank's non-empty data wins
        if (agg.data.empty() && !m.data.empty()) agg.data = m.data;
      }
      participants_.clear();
      Millis timeout(std::max<int64_t>(ms_until(deadline), 1));
      // Reap workers from completed rounds before spawning the next.
      for (auto it = quorum_workers_.begin(); it != quorum_workers_.end();) {
        if ((*it)->done.load()) {
          if ((*it)->thread.joinable()) (*it)->thread.join();
          it = quorum_workers_.erase(it);
        } else {
          ++it;
        }
      }
      auto slot = std::make_unique<WorkerSlot>();
      WorkerSlot* slot_ptr = slot.get();
      slot_ptr->thread = std::thread([this, agg, timeout, slot_ptr] {
        run_lighthouse_quorum(agg, timeout);
        slot_ptr->done.store(true);
      });
      quorum_workers_.push_back(std::move(slot));
    }

    bool got = quorum_cv_.wait_until(lk, deadline, [&] {
      return !running_.load() || quorum_gen_ > waiting_gen;
    });
    if (!running_.load())
      throw RpcError("unavailable", "manager shutting down");
    if (!got)
      throw TimeoutError("manager quorum timed out waiting for group barrier");
    if (!quorum_error_.empty()) throw RpcError("internal", quorum_error_);

    log_info(opts_.replica_id,
             "Finished quorum for group_rank " + std::to_string(group_rank));
    ManagerQuorumResult r = compute_quorum_results(
        opts_.replica_id, group_rank, *latest_quorum_, init_sync);
    return r.to_json();
  }
}

Json ManagerServer::rpc_checkpoint_metadata(const Json& params) {
  int64_t rank = params.get("rank").as_int();
  std::lock_guard<std::mutex> lk(mu_);
  auto it = checkpoint_metadata_.find(rank);
  if (it == checkpoint_metadata_.end())
    throw RpcError("invalid", "rank not found");
  Json j = Json::object();
  j["checkpoint_metadata"] = it->second;
  return j;
}

Json ManagerServer::rpc_should_commit(const Json& params, TimePoint deadline) {
  int64_t group_rank = params.get("group_rank").as_int();
  int64_t step = params.get_or("step", Json(int64_t(0))).as_int();
  bool should_commit = params.get("should_commit").as_bool();

  log_info(opts_.replica_id,
           "should_commit request from " + std::to_string(group_rank) +
               " should_commit=" + (should_commit ? "true" : "false"));

  std::unique_lock<std::mutex> lk(mu_);
  CommitRound& round = commit_rounds_[step];
  if (round.decided) {
    // A failed commit does not advance the step: the group re-votes the
    // SAME step after requorum. A decided round already holds every
    // rank's vote, so a new vote can only mean a retry round — reset.
    round = CommitRound{};
  }
  if (!round.decided) {
    if (!should_commit) round.fails.insert(group_rank);
    round.votes.insert(group_rank);
    if (static_cast<int64_t>(round.votes.size()) == opts_.world_size) {
      round.decided = true;
      round.decision = round.fails.empty();
      log_info(opts_.replica_id,
               std::string("should_commit completed should_commit=") +
                   (round.decision ? "true" : "false"));
      // prune decided rounds older than this step (bounded memory; a
      // straggler re-asking about a pruned step re-creates an empty round
      // and times out, which is the correct answer for ancient steps)
      for (auto it = commit_rounds_.begin(); it != commit_rounds_.end();) {
        if (it->first < step && it->second.decided)
          it = commit_rounds_.erase(it);
        else
          ++it;
      }
      commit_cv_.notify_all();
    } else {
      bool got = commit_cv_.wait_until(lk, deadline, [&] {
        return !running_.load() || commit_rounds_[step].decided;
      });
      if (!running_.load())
        throw RpcError("unavailable", "manager shutting down");
      if (!got) {
        // withdraw this rank's vote from the abandoned round: leaving it
        // would let a straggler later complete the round with residue from
        // an aborted attempt (stale fail vetoing a clean retry, or a
        // decision this caller never observes)
        CommitRound& r2 = commit_rounds_[step];
        if (!r2.decided) {
          r2.votes.erase(group_rank);
          r2.fails.erase(group_rank);
          if (r2.votes.empty()) commit_rounds_.erase(step);
        }
        throw TimeoutError("should_commit timed out waiting for votes");
      }
    }
  }

  Json j = Json::object();
  j["should_commit"] = commit_rounds_[step].decision;
  return j;
}

}  // namespace tft
