#include "healthwatch.h"

#include <algorithm>
#include <cmath>

#include "quorum.h"  // epoch_millis_now

namespace tft {

namespace {

constexpr size_t kMaxRecentEvents = 64;

double median_of(std::vector<double> v) {
  if (v.empty()) return 0.0;
  std::sort(v.begin(), v.end());
  size_t n = v.size();
  if (n % 2 == 1) return v[n / 2];
  return 0.5 * (v[n / 2 - 1] + v[n / 2]);
}

}  // namespace

HealthOpts HealthOpts::from_json(const Json& j) {
  HealthOpts o;
  o.mode = j.get_or("mode", Json(o.mode)).as_string();
  o.window = j.get_or("window", Json(o.window)).as_int();
  o.min_samples = j.get_or("min_samples", Json(o.min_samples)).as_int();
  o.warn_z = j.get_or("warn_z", Json(o.warn_z)).as_double();
  o.eject_z = j.get_or("eject_z", Json(o.eject_z)).as_double();
  o.eject_steps = j.get_or("eject_steps", Json(o.eject_steps)).as_int();
  o.probation_ms = j.get_or("probation_ms", Json(o.probation_ms)).as_int();
  o.probe_ok = j.get_or("probe_ok", Json(o.probe_ok)).as_int();
  o.rel_floor = j.get_or("rel_floor", Json(o.rel_floor)).as_double();
  return o;
}

Json HealthOpts::to_json() const {
  Json j = Json::object();
  j["mode"] = mode;
  j["window"] = window;
  j["min_samples"] = min_samples;
  j["warn_z"] = warn_z;
  j["eject_z"] = eject_z;
  j["eject_steps"] = eject_steps;
  j["probation_ms"] = probation_ms;
  j["probe_ok"] = probe_ok;
  j["rel_floor"] = rel_floor;
  return j;
}

const char* health_state_name(HealthState s) {
  switch (s) {
    case HealthState::kOk: return "ok";
    case HealthState::kWarn: return "warn";
    case HealthState::kEjected: return "ejected";
    case HealthState::kProbation: return "probation";
    case HealthState::kDegraded: return "degraded";
  }
  return "ok";
}

std::map<std::string, double> straggler_scores(
    const std::map<std::string, std::vector<double>>& windows,
    const HealthOpts& opts) {
  std::map<std::string, double> scores;
  // Per-replica robust statistic: the median of its window.
  std::map<std::string, double> stats;
  for (const auto& [rid, w] : windows) {
    scores[rid] = 0.0;
    if (static_cast<int64_t>(w.size()) >= opts.min_samples)
      stats[rid] = median_of(w);
  }
  if (stats.size() < 2) return scores;  // no peer group to compare against

  std::vector<double> xs;
  for (const auto& [rid, x] : stats) xs.push_back(x);
  double med = median_of(xs);
  std::vector<double> devs;
  for (double x : xs) devs.push_back(std::fabs(x - med));
  double mad = median_of(devs);
  // Modified z-score scale, floored: MAD is 0 on a homogeneous fleet (the
  // straggler is the only deviation and the median of deviations vanishes),
  // so fall back to a fraction of the median itself.
  double scale = std::max({mad / 0.6745, opts.rel_floor * std::max(med, 0.0),
                           1e-9});
  for (const auto& [rid, x] : stats)
    scores[rid] = std::max(0.0, x - med) / scale;  // only SLOW is unhealthy
  return scores;
}

HealthLedger::HealthLedger(HealthOpts opts, int64_t heartbeat_timeout_ms,
                           int64_t min_replicas)
    : opts_(std::move(opts)),
      heartbeat_timeout_ms_(heartbeat_timeout_ms),
      min_replicas_(min_replicas) {}

bool HealthLedger::can_eject(TimePoint now) const {
  // Ejecting must leave at least min_replicas live, non-excluded replicas.
  int64_t live = 0;
  for (const auto& [rid, rh] : replicas_) {
    if (excluded_.count(rid)) continue;
    if (now - rh.last_beat < Millis(heartbeat_timeout_ms_)) live += 1;
  }
  return live - 1 >= min_replicas_;
}

void HealthLedger::eject(const std::string& rid, ReplicaHealth& rh,
                         TimePoint now, std::vector<Json>* events) {
  rh.state = HealthState::kEjected;
  rh.ejections += 1;
  rh.strikes = 0;
  rh.probes_ok = 0;
  rh.ejected_at = now;
  // Probation judges post-recovery samples only. last_step is kept: the
  // beat loop keeps re-sending the last pre-ejection (dilated) telemetry
  // until the replica actually steps again, and re-ingesting it on the
  // first probation beat would re-eject a replica that never got to run.
  rh.window.clear();
  excluded_.insert(rid);
  Json e = Json::object();
  e["kind"] = std::string("eject");
  e["replica_id"] = rid;
  e["score"] = rh.score;
  e["ejections"] = rh.ejections;
  e["ms"] = epoch_millis_now();
  events->push_back(e);
}

void HealthLedger::evaluate(const std::string& rid, TimePoint now,
                            std::vector<Json>* events) {
  std::map<std::string, std::vector<double>> windows;
  for (const auto& [r, rh] : replicas_) {
    if (excluded_.count(r)) continue;  // ejected replicas have no window
    windows[r] = std::vector<double>(rh.window.begin(), rh.window.end());
  }
  auto scores = straggler_scores(windows, opts_);
  for (auto& [r, rh] : replicas_)
    if (scores.count(r)) rh.score = scores[r];

  auto it = replicas_.find(rid);
  if (it == replicas_.end()) return;
  ReplicaHealth& rh = it->second;
  double s = rh.score;

  if (rh.state == HealthState::kDegraded) {
    // Capacity-scaled samples keep the peer statistics honest, but a
    // degraded replica never accumulates strikes and never warns: it is
    // slow-but-alive by declaration, and ejecting it would turn a
    // survivable chip loss into a whole-group loss.
    rh.strikes = 0;
    return;
  }

  if (rh.state == HealthState::kProbation) {
    if (s > opts_.eject_z) {  // one strike in probation: straight back out
      if (opts_.mode == "eject" && can_eject(now)) {
        eject(rid, rh, now, events);
      }
      return;
    }
    // probes only count once the rebuilt window is scorable — an unscored
    // warmup sample (score pinned at 0) says nothing about recovery
    if (static_cast<int64_t>(rh.window.size()) < opts_.min_samples) return;
    rh.probes_ok += 1;
    if (rh.probes_ok >= opts_.probe_ok) {
      rh.state = s > opts_.warn_z ? HealthState::kWarn : HealthState::kOk;
      rh.probes_ok = 0;
    }
    return;
  }

  // ok / warn
  if (s > opts_.eject_z)
    rh.strikes += 1;
  else
    rh.strikes = 0;

  if (s > opts_.warn_z && rh.state == HealthState::kOk) {
    rh.state = HealthState::kWarn;
    Json e = Json::object();
    e["kind"] = std::string("straggler_warn");
    e["replica_id"] = rid;
    e["score"] = s;
    e["warn_z"] = opts_.warn_z;
    e["ms"] = epoch_millis_now();
    events->push_back(e);
  } else if (s <= opts_.warn_z && rh.state == HealthState::kWarn) {
    rh.state = HealthState::kOk;
  }

  if (rh.strikes >= opts_.eject_steps) {
    if (opts_.mode == "eject" && can_eject(now)) {
      eject(rid, rh, now, events);
    } else {
      // observe mode (or ejection would drop below min_replicas): report
      // that the policy WOULD eject, re-arm instead of spamming per sample
      Json e = Json::object();
      e["kind"] = std::string("straggler_warn");
      e["replica_id"] = rid;
      e["score"] = s;
      e["would_eject"] = true;
      e["reason"] = opts_.mode == "eject"
                        ? std::string("min_replicas floor")
                        : std::string("mode=") + opts_.mode;
      e["ms"] = epoch_millis_now();
      events->push_back(e);
      rh.strikes = 0;
    }
  }
}

std::vector<Json> HealthLedger::on_heartbeat(const std::string& rid,
                                             const Json* telemetry,
                                             TimePoint now) {
  std::vector<Json> events;
  if (opts_.mode == "off") return events;
  ReplicaHealth& rh = replicas_[rid];
  bool first = rh.samples_total == 0 && rh.last_beat == TimePoint{};
  // Probation demands CONTINUOUS fresh beats: a gap restarts the clock.
  if (rh.state == HealthState::kEjected && !first &&
      now - rh.last_beat > Millis(heartbeat_timeout_ms_))
    rh.ejected_at = now;
  rh.last_beat = now;

  if (telemetry != nullptr && telemetry->is_object() &&
      telemetry->contains("step") && rh.state != HealthState::kEjected) {
    int64_t step = telemetry->get("step").as_int();
    if (step > rh.last_step) {  // dedup: the beat loop re-sends the latest
      rh.last_step = step;
      double step_s = telemetry->get_or("step_s", Json(0.0)).as_double();
      double wire_s = telemetry->get_or("wire_s", Json(0.0)).as_double();
      rh.last_step_s = step_s;
      rh.last_wire_s = wire_s;
      // Score compute time, not wall time: the allreduce barrier equalizes
      // wall time across the quorum (everyone waits for the straggler), so
      // the straggler is the replica with high step_s minus wire wait.
      double sample = std::max(step_s - wire_s, 0.0);
      // Degrade plane: a replica at reduced group degree self-reports its
      // capacity; its compute sample is scaled to the full-capacity
      // equivalent so it is scored against what the step SHOULD cost and
      // never strike-ejected for being legitimately slower. Beats without
      // both keys take the exact pre-degrade path.
      if (telemetry->contains("group_world_size") &&
          telemetry->contains("full_group_world_size")) {
        int64_t gws = telemetry->get("group_world_size").as_int();
        int64_t full = telemetry->get("full_group_world_size").as_int();
        rh.group_world_size = gws;
        rh.full_group_world_size = full;
        if (0 < gws && gws < full) {
          sample *= static_cast<double>(gws) / static_cast<double>(full);
          if (rh.state == HealthState::kOk ||
              rh.state == HealthState::kWarn) {
            rh.state = HealthState::kDegraded;
            rh.strikes = 0;
            Json e = Json::object();
            e["kind"] = std::string("degrade");
            e["replica_id"] = rid;
            e["group_world_size"] = gws;
            e["full_group_world_size"] = full;
            e["ms"] = epoch_millis_now();
            events.push_back(e);
          }
        } else if (rh.state == HealthState::kDegraded && full > 0 &&
                   gws >= full) {
          rh.state = HealthState::kOk;
          Json e = Json::object();
          e["kind"] = std::string("restore");
          e["replica_id"] = rid;
          e["group_world_size"] = gws;
          e["ms"] = epoch_millis_now();
          events.push_back(e);
        }
      }
      rh.window.push_back(sample);
      while (static_cast<int64_t>(rh.window.size()) > opts_.window)
        rh.window.pop_front();
      rh.samples_total += 1;
      evaluate(rid, now, &events);
    }
  }
  remember(events);
  return events;
}

std::vector<Json> HealthLedger::tick(TimePoint now, int64_t prune_after_ms) {
  std::vector<Json> events;
  if (opts_.mode == "off") return events;
  for (auto it = replicas_.begin(); it != replicas_.end();) {
    const std::string& rid = it->first;
    ReplicaHealth& rh = it->second;
    if (now - rh.last_beat > Millis(prune_after_ms)) {
      excluded_.erase(rid);
      it = replicas_.erase(it);
      continue;
    }
    if (rh.state == HealthState::kEjected &&
        now - rh.ejected_at >= Millis(opts_.probation_ms) &&
        now - rh.last_beat < Millis(heartbeat_timeout_ms_)) {
      rh.state = HealthState::kProbation;
      rh.readmissions += 1;
      rh.probes_ok = 0;
      excluded_.erase(rid);
      Json e = Json::object();
      e["kind"] = std::string("readmit");
      e["replica_id"] = rid;
      e["readmissions"] = rh.readmissions;
      e["ms"] = epoch_millis_now();
      events.push_back(e);
    }
    ++it;
  }
  remember(events);
  return events;
}

void HealthLedger::remember(const std::vector<Json>& events) {
  for (const auto& e : events) {
    recent_events_.push_back(e);
    while (recent_events_.size() > kMaxRecentEvents) recent_events_.pop_front();
  }
}

Json HealthLedger::replica_json(const std::string& rid) const {
  Json j = Json::object();
  j["mode"] = opts_.mode;
  auto it = replicas_.find(rid);
  if (it == replicas_.end()) {
    j["state"] = std::string("ok");
    j["state_code"] = int64_t{0};
    return j;
  }
  const ReplicaHealth& rh = it->second;
  j["state"] = std::string(health_state_name(rh.state));
  j["state_code"] = static_cast<int64_t>(rh.state);
  j["score"] = rh.score;
  j["samples"] = rh.samples_total;
  j["ejections"] = rh.ejections;
  j["readmissions"] = rh.readmissions;
  if (rh.full_group_world_size > 0) {
    j["group_world_size"] = rh.group_world_size;
    j["full_group_world_size"] = rh.full_group_world_size;
  }
  return j;
}

Json HealthLedger::to_json(TimePoint now) const {
  Json j = Json::object();
  j["mode"] = opts_.mode;
  j["opts"] = opts_.to_json();
  Json reps = Json::object();
  for (const auto& [rid, rh] : replicas_) {
    Json r = Json::object();
    r["state"] = std::string(health_state_name(rh.state));
    r["score"] = rh.score;
    r["samples"] = rh.samples_total;
    r["window"] = static_cast<int64_t>(rh.window.size());
    r["window_median"] =
        median_of(std::vector<double>(rh.window.begin(), rh.window.end()));
    r["last_step"] = rh.last_step;
    r["last_step_s"] = rh.last_step_s;
    r["last_wire_s"] = rh.last_wire_s;
    r["strikes"] = rh.strikes;
    r["ejections"] = rh.ejections;
    r["readmissions"] = rh.readmissions;
    if (rh.full_group_world_size > 0) {
      r["group_world_size"] = rh.group_world_size;
      r["full_group_world_size"] = rh.full_group_world_size;
    }
    r["last_beat_ms_ago"] = static_cast<int64_t>(
        std::chrono::duration_cast<Millis>(now - rh.last_beat).count());
    reps[rid] = r;
  }
  j["replicas"] = reps;
  Json ex = Json::array();
  for (const auto& rid : excluded_) ex.push_back(rid);
  j["excluded"] = ex;
  Json ev = Json::array();
  for (const auto& e : recent_events_) ev.push_back(e);
  j["recent_events"] = ev;
  return j;
}

}  // namespace tft
