// Per-replica-group manager service (runs alongside group_rank 0).
// Equivalent of the reference's Rust Manager (src/manager.rs:80-486):
// aggregates the group's ranks — when all world_size ranks call quorum it
// forwards a single request to the lighthouse (with retries) and broadcasts
// the result; computes per-rank recovery assignments; runs the 2-phase
// should_commit vote; stores per-rank checkpoint metadata; Kill exits the
// process; background heartbeat loop to the lighthouse.
#pragma once

#include <condition_variable>
#include <map>
#include <memory>
#include <mutex>
#include <set>
#include <thread>

#include "quorum.h"
#include "wire.h"

namespace tft {

struct ManagerOpts {
  std::string replica_id;
  std::string lighthouse_addr;
  // Optional pod aggregator (aggregator.h) to prefer for heartbeats and
  // quorum; empty = flat fleet, talk to the lighthouse directly. When the
  // aggregator dies the manager fails over to direct-to-root mode on its
  // own and re-points when the root names a replacement.
  std::string aggregator_addr;
  std::string hostname;       // advertised host for this manager
  std::string bind;           // "host:port", port 0 = ephemeral
  std::string store_addr;     // rendezvous KV store address for this replica
  int64_t world_size = 1;     // ranks inside this replica group
  int64_t heartbeat_interval_ms = 100;
  int64_t connect_timeout_ms = 10000;
  int64_t quorum_retries = 0;
};

class ManagerServer {
 public:
  explicit ManagerServer(ManagerOpts opts);
  ~ManagerServer();

  int port() const { return server_->port(); }
  std::string address() const;
  void shutdown();

  // Healthwatch: the Manager publishes per-step telemetry (step, step_s,
  // wire_s, counters) and the beat loop piggybacks the latest payload on
  // every heartbeat; the lighthouse's response carries this replica's
  // health summary back, readable via health_json().
  void publish_telemetry(const std::string& telemetry_json);
  std::string health_json() const;  // "{}" until the first beat round-trips

  // Policy plane: the latest versioned policy frame carried on a heartbeat
  // reply (directly from the root, or fanned out by the pod aggregator).
  // "{}" until a frame arrives. The Manager polls this at its quorum safe
  // point; the beat loop never interprets the frame.
  std::string policy_json() const;

  // Clock skew vs the lighthouse, estimated from heartbeat round-trips:
  // the midpoint of this side's send/receive epoch times minus the
  // response's server_ms — replica-minus-lighthouse, positive when this
  // host's clock runs ahead (merge_traces subtracts skew_ms to land on
  // the lighthouse's clock). The kept estimate is the one from the
  // minimum-RTT beat (least queueing noise). JSON: {"skew_ms", "rtt_ms",
  // "last_skew_ms", "last_rtt_ms", "samples"}; samples=0 until the first
  // beat round-trips against a server_ms-aware lighthouse.
  std::string clock_skew_json() const;

  // Two-level control plane view: {"aggregator_addr", "via_aggregator",
  // "direct_mode", "failovers"} — which upstream the control RPCs are using
  // and how many aggregator->root failovers happened.
  std::string control_status_json() const;

 private:
  Json handle(const std::string& method, const Json& params, TimePoint deadline);
  Json rpc_quorum(const Json& params, TimePoint deadline);
  Json rpc_checkpoint_metadata(const Json& params);
  Json rpc_should_commit(const Json& params, TimePoint deadline);

  void heartbeat_loop();
  // Runs on a detached worker when the last rank arrives.
  void run_lighthouse_quorum(QuorumMember member, Millis timeout);

  ManagerOpts opts_;
  std::mutex mu_;

  // Quorum barrier + broadcast.
  std::condition_variable quorum_cv_;
  std::map<int64_t, QuorumMember> participants_;
  uint64_t quorum_gen_ = 0;
  std::optional<QuorumSnapshot> latest_quorum_;
  std::string quorum_error_;  // non-empty -> last round failed

  // Per-rank checkpoint metadata (healing peers fetch these).
  std::map<int64_t, std::string> checkpoint_metadata_;

  // 2-phase commit vote, keyed by step: votes from a timed-out or earlier
  // round must never complete (or veto) a later step's round.
  struct CommitRound {
    std::set<int64_t> votes;
    std::set<int64_t> fails;
    bool decided = false;
    bool decision = false;
  };
  std::condition_variable commit_cv_;
  std::map<int64_t, CommitRound> commit_rounds_;

  // Telemetry/health exchange with the beat loop; separate mutex so a
  // publish from the training hot loop never waits behind a quorum barrier
  // holding mu_.
  mutable std::mutex telemetry_mu_;
  Json telemetry_;            // latest published payload (null = none)
  std::string last_health_;   // last heartbeat response's "health" field
  std::string last_policy_;   // last heartbeat response's "policy" frame
  // Skew estimate state (guarded by telemetry_mu_).
  double best_skew_ms_ = 0.0;
  double best_rtt_ms_ = 0.0;
  double last_skew_ms_ = 0.0;
  double last_rtt_ms_ = 0.0;
  int64_t skew_samples_ = 0;

  std::atomic<bool> running_{true};
  std::unique_ptr<RpcServer> server_;
  std::thread heartbeat_thread_;
  // One slot per in-flight lighthouse-quorum worker; finished slots are
  // reaped when the next round spawns (and all joined at shutdown).
  struct WorkerSlot {
    std::thread thread;
    std::atomic<bool> done{false};
  };
  std::vector<std::unique_ptr<WorkerSlot>> quorum_workers_;
  // Separate cached-connection clients so the 100ms heartbeat never queues
  // behind a long-blocking lighthouse quorum call.
  std::unique_ptr<RpcClient> heartbeat_client_;
  std::unique_ptr<RpcClient> quorum_client_;

  // Aggregator failover state. agg_mu_ guards the address + clients (the
  // root can re-point us at a replacement mid-run); shared_ptr so a beat
  // in flight on the old client survives a concurrent re-point.
  std::shared_ptr<RpcClient> agg_client(bool for_quorum) const;
  void adopt_aggregator(const std::string& addr);
  mutable std::mutex agg_mu_;
  std::string agg_addr_;  // current aggregator ("" = flat fleet)
  std::shared_ptr<RpcClient> agg_heartbeat_client_;
  std::shared_ptr<RpcClient> agg_quorum_client_;
  std::atomic<bool> agg_down_{false};
  std::atomic<int64_t> agg_failovers_{0};
};

}  // namespace tft
