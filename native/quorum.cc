#include "quorum.h"

#include <algorithm>
#include <set>

#include "wire.h"

namespace tft {

int64_t epoch_millis_now() {
  return std::chrono::duration_cast<std::chrono::milliseconds>(
             std::chrono::system_clock::now().time_since_epoch())
      .count();
}

Json QuorumMember::to_json() const {
  Json j = Json::object();
  j["replica_id"] = replica_id;
  j["address"] = address;
  j["store_address"] = store_address;
  j["step"] = step;
  j["world_size"] = world_size;
  j["shrink_only"] = shrink_only;
  j["commit_failures"] = commit_failures;
  j["data"] = data;
  return j;
}

QuorumMember QuorumMember::from_json(const Json& j) {
  QuorumMember m;
  m.replica_id = j.get("replica_id").as_string();
  m.address = j.get_or("address", Json("")).as_string();
  m.store_address = j.get_or("store_address", Json("")).as_string();
  m.step = j.get_or("step", Json(int64_t{0})).as_int();
  m.world_size = j.get_or("world_size", Json(int64_t{1})).as_int();
  m.shrink_only = j.get_or("shrink_only", Json(false)).as_bool();
  m.commit_failures = j.get_or("commit_failures", Json(int64_t{0})).as_int();
  m.data = j.get_or("data", Json("")).as_string();
  return m;
}

Json QuorumSnapshot::to_json() const {
  Json j = Json::object();
  j["quorum_id"] = quorum_id;
  Json parts = Json::array();
  for (const auto& p : participants) parts.push_back(p.to_json());
  j["participants"] = parts;
  j["created_ms"] = created_ms;
  return j;
}

QuorumSnapshot QuorumSnapshot::from_json(const Json& j) {
  QuorumSnapshot q;
  q.quorum_id = j.get("quorum_id").as_int();
  for (const auto& p : j.get("participants").as_array())
    q.participants.push_back(QuorumMember::from_json(p));
  q.created_ms = j.get_or("created_ms", Json(int64_t{0})).as_int();
  return q;
}

bool quorum_changed(const std::vector<QuorumMember>& a,
                    const std::vector<QuorumMember>& b) {
  if (a.size() != b.size()) return true;
  for (size_t i = 0; i < a.size(); ++i)
    if (a[i].replica_id != b[i].replica_id) return true;
  return false;
}

std::pair<std::optional<std::vector<QuorumMember>>, std::string> quorum_compute(
    TimePoint now, const LighthouseState& state, const LighthouseOpts& opts) {
  // Health: a replica is healthy if its last heartbeat is fresh AND the
  // health ledger has not ejected it. Ejected replicas drop out of the
  // healthy count entirely — they must neither join the quorum nor veto
  // the majority / all-joined checks while serving their probation.
  std::set<std::string> healthy_replicas;
  for (const auto& [rid, last] : state.heartbeats) {
    if (now - last < Millis(opts.heartbeat_timeout_ms) &&
        !state.excluded.count(rid))
      healthy_replicas.insert(rid);
  }

  std::map<std::string, const MemberDetails*> healthy_participants;
  for (const auto& [rid, details] : state.participants) {
    if (healthy_replicas.count(rid)) healthy_participants[rid] = &details;
  }

  std::vector<QuorumMember> candidates;
  for (const auto& [rid, details] : healthy_participants)
    candidates.push_back(details->member);
  // std::map iteration is already sorted by replica_id -> deterministic order.

  bool shrink_only = std::any_of(
      healthy_participants.begin(), healthy_participants.end(),
      [](const auto& kv) { return kv.second->member.shrink_only; });

  std::string metadata = "[" + std::to_string(healthy_participants.size()) +
                         "/" + std::to_string(state.participants.size()) +
                         " participants healthy][" +
                         std::to_string(healthy_replicas.size()) +
                         " heartbeating][" +
                         std::to_string(state.excluded.size()) +
                         " excluded][shrink_only=" +
                         (shrink_only ? "true" : "false") + "]";

  // Fast quorum: every member of the previous quorum is healthy and has
  // re-joined -> no need to wait for the join timeout.
  if (state.prev_quorum.has_value()) {
    const auto& prev = *state.prev_quorum;
    if (shrink_only) {
      std::set<std::string> prev_ids;
      for (const auto& p : prev.participants) prev_ids.insert(p.replica_id);
      std::vector<QuorumMember> filtered;
      for (auto& c : candidates)
        if (prev_ids.count(c.replica_id)) filtered.push_back(c);
      candidates = std::move(filtered);
    }
    bool fast = std::all_of(
        prev.participants.begin(), prev.participants.end(),
        [&](const QuorumMember& m) {
          return healthy_participants.count(m.replica_id) > 0;
        });
    if (fast) {
      return {candidates, "Fast quorum found! " + metadata};
    }
  }

  // min_replicas applies to the PUBLISHABLE candidate list: under
  // shrink_only the candidates were filtered to previous-quorum members,
  // and a quorum below min_replicas must not be published just because the
  // unfiltered healthy count passed. (The majority guard below stays on
  // the unfiltered health counts, matching the reference — shrink_only
  // excludes new joiners by design, and they must not veto the shrink.)
  if (static_cast<int64_t>(candidates.size()) < opts.min_replicas) {
    return {std::nullopt,
            "New quorum not ready, only have " +
                std::to_string(candidates.size()) +
                " participants, need min_replicas " +
                std::to_string(opts.min_replicas) + " " + metadata};
  }

  // Split-brain guard: require a strict majority of known-alive replicas.
  if (healthy_participants.size() <= healthy_replicas.size() / 2) {
    return {std::nullopt,
            "New quorum not ready, only have " +
                std::to_string(healthy_participants.size()) +
                " participants, need at least half of " +
                std::to_string(healthy_replicas.size()) + " healthy workers " +
                metadata};
  }

  // Wait for stragglers that are alive but haven't re-joined yet, up to the
  // join timeout measured from the first joiner.
  bool all_healthy_joined =
      healthy_participants.size() == healthy_replicas.size();
  TimePoint first_joined = now;
  for (const auto& [rid, details] : healthy_participants)
    first_joined = std::min(first_joined, details->joined);
  if (!all_healthy_joined &&
      now - first_joined < Millis(opts.join_timeout_ms)) {
    return {std::nullopt,
            "Valid quorum with " +
                std::to_string(healthy_participants.size()) +
                " participants, waiting for " +
                std::to_string(healthy_replicas.size() -
                               healthy_participants.size()) +
                " healthy but not participating stragglers due to join "
                "timeout " +
                metadata};
  }

  return {candidates, "Valid quorum found " + metadata};
}

Json ManagerQuorumResult::to_json() const {
  Json j = Json::object();
  j["quorum_id"] = quorum_id;
  j["recover_src_manager_address"] = recover_src_manager_address;
  j["recover_src_replica_rank"] =
      recover_src_replica_rank ? Json(*recover_src_replica_rank) : Json();
  Json fallbacks = Json::array();
  for (const auto& f : recover_src_fallbacks) {
    Json fj = Json::object();
    fj["replica_rank"] = f.replica_rank;
    fj["address"] = f.address;
    fallbacks.push_back(fj);
  }
  j["recover_src_fallbacks"] = fallbacks;
  Json dsts = Json::array();
  for (auto r : recover_dst_replica_ranks) dsts.push_back(r);
  j["recover_dst_replica_ranks"] = dsts;
  j["store_address"] = store_address;
  j["max_step"] = max_step;
  j["max_replica_rank"] = max_replica_rank ? Json(*max_replica_rank) : Json();
  j["max_world_size"] = max_world_size;
  j["replica_rank"] = replica_rank;
  j["replica_world_size"] = replica_world_size;
  j["heal"] = heal;
  j["commit_failures"] = commit_failures;
  Json ids = Json::array();
  for (const auto& id : replica_ids) ids.push_back(id);
  j["replica_ids"] = ids;
  return j;
}

ManagerQuorumResult compute_quorum_results(const std::string& replica_id,
                                           int64_t group_rank,
                                           const QuorumSnapshot& quorum,
                                           bool init_sync) {
  std::vector<QuorumMember> participants = quorum.participants;
  std::sort(participants.begin(), participants.end(),
            [](const QuorumMember& a, const QuorumMember& b) {
              return a.replica_id < b.replica_id;
            });

  int64_t replica_rank = -1;
  for (size_t i = 0; i < participants.size(); ++i) {
    if (participants[i].replica_id == replica_id) {
      replica_rank = static_cast<int64_t>(i);
      break;
    }
  }
  if (replica_rank < 0)
    throw RpcError("not_found", "replica " + replica_id +
                                    " not participating in returned quorum");

  int64_t max_step = 0;
  for (const auto& p : participants) max_step = std::max(max_step, p.step);

  std::vector<size_t> max_idx;  // indices of participants at max_step
  for (size_t i = 0; i < participants.size(); ++i)
    if (participants[i].step == max_step) max_idx.push_back(i);

  std::optional<int64_t> max_replica_rank;
  for (size_t i = 0; i < max_idx.size(); ++i)
    if (participants[max_idx[i]].replica_id == replica_id)
      max_replica_rank = static_cast<int64_t>(i);

  // One KV store per replica group; ranks of each group spread across the
  // stores of the max-step participants for load balancing.
  const QuorumMember& primary =
      participants[max_idx[static_cast<size_t>(group_rank) % max_idx.size()]];

  // A replica recovers if it is behind, or (on a cold start with init_sync)
  // if it is not the primary — forcing everyone to adopt the primary's
  // initialization so all replicas start bitwise identical.
  bool force_recover = init_sync && max_step == 0;
  std::vector<size_t> recovering;
  for (size_t i = 0; i < participants.size(); ++i) {
    const auto& p = participants[i];
    if (p.step != max_step ||
        (force_recover && primary.replica_id != p.replica_id))
      recovering.push_back(i);
  }
  std::set<size_t> recovering_set(recovering.begin(), recovering.end());
  std::vector<size_t> up_to_date;
  for (size_t i = 0; i < participants.size(); ++i)
    if (!recovering_set.count(i)) up_to_date.push_back(i);

  // Round-robin assignment of recovery sources, offset by group_rank so the
  // ranks of one recovering replica spread their fetches across sources.
  std::map<size_t, std::vector<int64_t>> assignments;  // src idx -> dst ranks
  std::optional<int64_t> recover_src_replica_rank;
  for (size_t i = 0; i < recovering.size(); ++i) {
    size_t src =
        up_to_date[(i + static_cast<size_t>(group_rank)) % up_to_date.size()];
    assignments[src].push_back(static_cast<int64_t>(recovering[i]));
    if (static_cast<int64_t>(recovering[i]) == replica_rank)
      recover_src_replica_rank = static_cast<int64_t>(src);
  }

  ManagerQuorumResult r;
  r.quorum_id = quorum.quorum_id;
  r.recover_src_replica_rank = recover_src_replica_rank;
  r.recover_src_manager_address =
      recover_src_replica_rank
          ? participants[static_cast<size_t>(*recover_src_replica_rank)].address
          : "";
  if (recover_src_replica_rank) {
    // Remaining up-to-date peers in round-robin order starting just after
    // the assigned source, so concurrent failovers spread across sources
    // the same way the primary assignment does.
    size_t src_pos = 0;
    for (size_t i = 0; i < up_to_date.size(); ++i)
      if (static_cast<int64_t>(up_to_date[i]) == *recover_src_replica_rank)
        src_pos = i;
    for (size_t i = 1; i < up_to_date.size(); ++i) {
      size_t idx = up_to_date[(src_pos + i) % up_to_date.size()];
      FallbackPeer f;
      f.replica_rank = static_cast<int64_t>(idx);
      f.address = participants[idx].address;
      r.recover_src_fallbacks.push_back(f);
    }
  }
  auto it = assignments.find(static_cast<size_t>(replica_rank));
  if (it != assignments.end()) r.recover_dst_replica_ranks = it->second;
  r.store_address = primary.store_address;
  r.max_step = max_step;
  r.max_replica_rank = max_replica_rank;
  r.max_world_size = static_cast<int64_t>(max_idx.size());
  r.replica_rank = replica_rank;
  r.replica_world_size = static_cast<int64_t>(participants.size());
  r.heal = recover_src_replica_rank.has_value();
  int64_t cf = 0;
  for (const auto& p : participants) cf = std::max(cf, p.commit_failures);
  r.commit_failures = cf;
  for (const auto& p : participants) r.replica_ids.push_back(p.replica_id);
  return r;
}

}  // namespace tft
