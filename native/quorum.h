// Pure quorum logic for the torchft_tpu control plane.
//
// Semantics match the reference implementation:
// - lighthouse quorum computation: heartbeat health, fast-quorum when all
//   previous members are healthy, min_replicas gate, split-brain majority
//   check, join-timeout straggler wait, shrink_only filtering
//   (reference: src/lighthouse.rs:141-269)
// - per-rank manager results: sorted replica ranks, max-step participants,
//   primary store selection, round-robin recovery assignment, init_sync
//   force-recovery (reference: src/manager.rs:489-625)
// These are pure functions over value types so they unit-test without any
// server running, exactly like the reference's Rust test suites.
#pragma once

#include <cstdint>
#include <map>
#include <optional>
#include <set>
#include <string>
#include <vector>

#include "json.h"
#include "net.h"

namespace tft {

struct QuorumMember {
  std::string replica_id;
  std::string address;        // manager RPC address (host:port)
  std::string store_address;  // rendezvous KV store address
  int64_t step = 0;
  int64_t world_size = 1;     // group world size (ranks inside the replica)
  bool shrink_only = false;
  int64_t commit_failures = 0;
  std::string data;           // user payload, JSON string

  Json to_json() const;
  static QuorumMember from_json(const Json& j);
  bool operator==(const QuorumMember& o) const {
    return replica_id == o.replica_id;
  }
};

struct QuorumSnapshot {
  int64_t quorum_id = 0;
  std::vector<QuorumMember> participants;
  int64_t created_ms = 0;  // epoch millis

  Json to_json() const;
  static QuorumSnapshot from_json(const Json& j);
};

struct LighthouseOpts {
  int64_t min_replicas = 1;
  int64_t join_timeout_ms = 60000;
  int64_t quorum_tick_ms = 100;
  int64_t heartbeat_timeout_ms = 5000;
  // Recorded-history JSONL path (history.h); empty = disabled.
  std::string history_path;
  // Policy event stream: >0 enables the in-memory history ring of that
  // capacity so the policy engine can fold live events without a file.
  int64_t policy_ring = 0;
  // /metrics cardinality cap: per-replica series are emitted for at most
  // this many replicas (lexicographic); the tail collapses into aggregate
  // min/median/max series so a 1000-replica fleet can't melt the scraper.
  int64_t metrics_per_replica_limit = 64;
};

struct MemberDetails {
  TimePoint joined;
  QuorumMember member;
};

struct LighthouseState {
  std::map<std::string, MemberDetails> participants;  // replica_id -> details
  std::map<std::string, TimePoint> heartbeats;        // replica_id -> last beat
  // Replicas proactively ejected by the health ledger (healthwatch.h).
  // Treated as unhealthy by quorum_compute even with fresh heartbeats, and
  // removed from the healthy count so they neither join nor veto a quorum.
  std::set<std::string> excluded;
  std::optional<QuorumSnapshot> prev_quorum;
  int64_t quorum_id = 0;
};

// Returns (participants or nullopt, human-readable reason).
std::pair<std::optional<std::vector<QuorumMember>>, std::string> quorum_compute(
    TimePoint now, const LighthouseState& state, const LighthouseOpts& opts);

// True if membership (ordered replica_id list) differs.
bool quorum_changed(const std::vector<QuorumMember>& a,
                    const std::vector<QuorumMember>& b);

struct FallbackPeer {
  int64_t replica_rank = 0;
  std::string address;  // manager RPC address (host:port)
};

struct ManagerQuorumResult {
  int64_t quorum_id = 0;
  std::string recover_src_manager_address;
  std::optional<int64_t> recover_src_replica_rank;
  // Other up-to-date (max_step) peers a healing replica can fail over to if
  // the assigned source dies mid-transfer, rotated to continue round-robin
  // after the assigned source. Empty unless heal is set.
  std::vector<FallbackPeer> recover_src_fallbacks;
  std::vector<int64_t> recover_dst_replica_ranks;
  std::string store_address;
  int64_t max_step = 0;
  std::optional<int64_t> max_replica_rank;
  int64_t max_world_size = 0;
  int64_t replica_rank = 0;
  int64_t replica_world_size = 0;
  bool heal = false;
  int64_t commit_failures = 0;
  std::vector<std::string> replica_ids;

  Json to_json() const;
};

// Throws RpcError("not_found") if replica_id is not in the quorum.
ManagerQuorumResult compute_quorum_results(const std::string& replica_id,
                                           int64_t group_rank,
                                           const QuorumSnapshot& quorum,
                                           bool init_sync);

int64_t epoch_millis_now();

}  // namespace tft
