#include "aggregator.h"

#include <algorithm>
#include <cstdio>
#include <sstream>

namespace tft {

namespace {
void log_info(const std::string& id, const std::string& msg) {
  std::fprintf(stderr, "[aggregator %s] %s\n", id.c_str(), msg.c_str());
}
}  // namespace

Aggregator::Aggregator(const std::string& bind, AggregatorOpts opts)
    : opts_(std::move(opts)), epoch_(epoch_millis_now()) {
  root_client_ = std::make_unique<RpcClient>(opts_.root_addr,
                                             Millis(opts_.connect_timeout_ms));
  server_ = std::make_unique<RpcServer>(
      bind,
      [this](const std::string& m, const Json& p, TimePoint d) {
        return handle(m, p, d);
      },
      [this](const std::string& m, const std::string& p) {
        return handle_http(m, p);
      });
  agg_id_ = opts_.agg_id.empty() ? address() : opts_.agg_id;
  tick_thread_ = std::thread([this] { tick_loop(); });
}

Aggregator::~Aggregator() { shutdown(); }

std::string Aggregator::address() const {
  return local_hostname() + ":" + std::to_string(server_->port());
}

void Aggregator::shutdown() {
  bool was = running_.exchange(false);
  if (!was) return;
  quorum_cv_.notify_all();
  tick_cv_.notify_all();
  if (tick_thread_.joinable()) tick_thread_.join();
  server_->shutdown();
}

Json Aggregator::handle(const std::string& method, const Json& params,
                        TimePoint deadline) {
  if (method == "heartbeat") return rpc_heartbeat(params);
  if (method == "quorum") return rpc_quorum(params, deadline);
  if (method == "status") return status_json();
  throw RpcError("invalid", "unknown aggregator method: " + method);
}

Json Aggregator::rpc_heartbeat(const Json& params) {
  std::string rid = params.get("replica_id").as_string();
  std::lock_guard<std::mutex> lk(mu_);
  PodReplica& r = pod_[rid];
  r.last_beat = Clock::now();
  if (params.contains("telemetry") && !params.get("telemetry").is_null()) {
    Json t = params.get("telemetry");
    int64_t step = t.get_or("step", Json(int64_t{-1})).as_int();
    // Delta cursor: only a step advance marks the payload dirty for the
    // next upstream tick (the flat protocol re-sends it every beat).
    if (step != r.telemetry_step) r.telemetry_step = step;
    r.telemetry = std::move(t);
  }
  // Same response shape as the lighthouse beat: the manager's skew
  // estimator and health mirror work unchanged against an aggregator.
  Json out = Json::object();
  out["health"] = r.health.is_null() ? Json::object() : r.health;
  out["server_ms"] = epoch_millis_now();
  out["aggregated"] = true;
  // Fan the root's policy frame out to the pod: one frame per tick rides
  // down to N replicas on replies they already receive. Absent until the
  // root publishes one, so pre-policy pods see an unchanged reply.
  if (policy_frame_.is_object()) out["policy"] = policy_frame_;
  return out;
}

Json Aggregator::rpc_quorum(const Json& params, TimePoint deadline) {
  QuorumMember requester = QuorumMember::from_json(params.get("requester"));
  const std::string& rid = requester.replica_id;
  log_info(agg_id_, "pod quorum request from " + rid);

  std::unique_lock<std::mutex> lk(mu_);
  pod_[rid].last_beat = Clock::now();  // implicit beat, like the lighthouse
  joiners_[rid] = PendingJoiner{requester, deadline};
  uint64_t waiting_gen = quorum_gen_;
  // Wake the tick loop so registration isn't delayed a full tick.
  tick_requested_ = true;
  tick_cv_.notify_all();

  // Same re-subscribe loop as the lighthouse: wait for a quorum containing
  // the requester; a quorum published without it re-registers and waits.
  while (true) {
    bool got = quorum_cv_.wait_until(lk, deadline, [&] {
      return !running_.load() || quorum_gen_ > waiting_gen;
    });
    if (!running_.load())
      throw RpcError("unavailable", "aggregator shutting down");
    if (!got) throw TimeoutError("quorum request timed out (aggregator)");
    waiting_gen = quorum_gen_;
    const QuorumSnapshot& q = *latest_quorum_;
    bool in_quorum = std::any_of(
        q.participants.begin(), q.participants.end(),
        [&](const QuorumMember& m) { return m.replica_id == rid; });
    if (in_quorum) {
      joiners_.erase(rid);
      Json out = Json::object();
      out["quorum"] = q.to_json();
      return out;
    }
    log_info(agg_id_, "replica " + rid + " not in quorum, re-registering");
    pod_[rid].last_beat = Clock::now();
    joiners_[rid] = PendingJoiner{requester, deadline};
    tick_requested_ = true;
    tick_cv_.notify_all();
  }
}

Json Aggregator::build_tick_frame_locked() {
  auto now = Clock::now();
  seq_ += 1;
  Json frame = Json::object();
  frame["agg_id"] = agg_id_;
  frame["addr"] = address();
  frame["epoch"] = epoch_;
  frame["seq"] = seq_;
  frame["quorum_gen_seen"] = root_quorum_gen_;

  // Live set: pod replicas with a fresh beat. Prune long-dead entries on
  // the same 10x horizon the lighthouse uses so pod churn stays bounded.
  std::set<std::string> live;
  for (auto it = pod_.begin(); it != pod_.end();) {
    auto age = now - it->second.last_beat;
    if (age > Millis(10 * opts_.heartbeat_timeout_ms)) {
      it = pod_.erase(it);
      continue;
    }
    if (age < Millis(opts_.heartbeat_timeout_ms)) live.insert(it->first);
    ++it;
  }
  if (last_tick_ok_ && live == last_live_sent_) {
    frame["beats_same"] = true;
  } else {
    Json beats = Json::array();
    for (const auto& rid : live) beats.push_back(rid);
    frame["beats"] = beats;
  }

  // Telemetry delta: only steps not yet acked upstream.
  Json tel = Json::object();
  for (auto& [rid, r] : pod_) {
    if (!live.count(rid)) continue;
    if (r.telemetry_step >= 0 && r.telemetry_step != r.forwarded_step)
      tel[rid] = r.telemetry;
  }
  if (tel.size() > 0) frame["telemetry"] = tel;

  // Pending quorum joiners (drop expired ones so the root's join-timeout
  // straggler wait isn't held open by an abandoned request).
  Json joiners = Json::array();
  for (auto it = joiners_.begin(); it != joiners_.end();) {
    if (now >= it->second.deadline) {
      it = joiners_.erase(it);
      continue;
    }
    joiners.push_back(it->second.member.to_json());
    ++it;
  }
  if (joiners.size() > 0) frame["joiners"] = joiners;

  // Stash the computed live set; it becomes the delta cursor only once the
  // root acks this frame (tick_loop's success path).
  pending_live_.swap(live);
  return frame;
}

void Aggregator::apply_tick_response_locked(const Json& resp) {
  // Health summaries fan back to the pod beats.
  if (resp.contains("health") && resp.get("health").is_object()) {
    for (const auto& [rid, h] : resp.get("health").as_object()) {
      auto it = pod_.find(rid);
      if (it != pod_.end()) it->second.health = h;
    }
  }
  if (resp.contains("quorum_gen"))
    root_quorum_gen_ = resp.get("quorum_gen").as_int();
  // Cache the newest policy frame for pod fan-out. Unknown response keys
  // are otherwise ignored (forward-compat: an older aggregator build
  // simply never looks at "policy" and keeps working).
  if (resp.contains("policy") && resp.get("policy").is_object())
    policy_frame_ = resp.get("policy");
  if (resp.contains("quorum") && !resp.get("quorum").is_null()) {
    latest_quorum_ = QuorumSnapshot::from_json(resp.get("quorum"));
    quorum_gen_ += 1;
    // Drop pending joiners this quorum satisfies right now, not when their
    // blocked handlers next get scheduled — otherwise the next tick frame
    // re-forwards them and the root re-registers replicas that are no
    // longer waiting. Handlers wake off latest_quorum_, not this map.
    for (const auto& m : latest_quorum_->participants) joiners_.erase(m.replica_id);
    quorum_cv_.notify_all();
  }
}

void Aggregator::tick_loop() {
  while (running_.load()) {
    Json frame;
    {
      std::lock_guard<std::mutex> lk(mu_);
      frame = build_tick_frame_locked();
    }
    std::string payload = frame.dump();
    try {
      Json resp = root_client_->call("agg_tick", frame,
                                     Millis(opts_.connect_timeout_ms));
      std::lock_guard<std::mutex> lk(mu_);
      ticks_ok_ += 1;
      upstream_bytes_ += payload.size();
      last_tick_ok_ = true;
      last_error_.clear();
      last_live_sent_ = pending_live_;
      // Ack the telemetry delta cursor for everything we just sent.
      if (frame.contains("telemetry")) {
        for (const auto& [rid, t] : frame.get("telemetry").as_object()) {
          (void)t;
          auto it = pod_.find(rid);
          if (it != pod_.end()) it->second.forwarded_step = it->second.telemetry_step;
        }
      }
      apply_tick_response_locked(resp);
    } catch (const std::exception& e) {
      std::lock_guard<std::mutex> lk(mu_);
      ticks_failed_ += 1;
      last_tick_ok_ = false;  // next frame re-sends the full live set
      if (last_error_ != e.what()) {
        last_error_ = e.what();
        log_info(agg_id_, std::string("upstream tick failed: ") + e.what());
      }
    }
    std::unique_lock<std::mutex> lk(mu_);
    tick_cv_.wait_for(lk, Millis(opts_.tick_ms), [&] {
      return !running_.load() || tick_requested_;
    });
    tick_requested_ = false;
  }
}

Json Aggregator::status_json() {
  std::lock_guard<std::mutex> lk(mu_);
  auto now = Clock::now();
  Json j = Json::object();
  j["agg_id"] = agg_id_;
  j["root_addr"] = opts_.root_addr;
  j["epoch"] = epoch_;
  j["seq"] = seq_;
  j["pod_size"] = static_cast<int64_t>(pod_.size());
  int64_t live = 0;
  for (const auto& [rid, r] : pod_)
    if (now - r.last_beat < Millis(opts_.heartbeat_timeout_ms)) live += 1;
  j["pod_live"] = live;
  j["joiners_pending"] = static_cast<int64_t>(joiners_.size());
  j["ticks_ok"] = static_cast<int64_t>(ticks_ok_);
  j["ticks_failed"] = static_cast<int64_t>(ticks_failed_);
  j["upstream_bytes"] = static_cast<int64_t>(upstream_bytes_);
  j["last_tick_ok"] = last_tick_ok_;
  j["last_error"] = last_error_;
  j["root_quorum_gen"] = root_quorum_gen_;
  j["rx"] = server_->rx_stats();
  return j;
}

std::tuple<std::string, std::string, std::string> Aggregator::handle_http(
    const std::string& method, const std::string& path) {
  (void)method;
  try {
    if (path == "/status" || path == "/" || path == "/index.html")
      return {"200 OK", "application/json", status_json().dump()};
    return {"404 Not Found", "text/plain", "not found"};
  } catch (const std::exception& e) {
    return {"500 Internal Server Error", "text/plain", e.what()};
  }
}

}  // namespace tft
