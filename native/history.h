// Recorded-history store: append-only JSONL of lighthouse control-plane
// events (quorum transitions, heals, health policy actions, telemetry
// snapshots). This is the replay substrate the ROADMAP's adaptive policy
// engine consumes: a policy candidate can be benched against the recorded
// fault/step history of a real run instead of a synthetic script.
//
// The write path lives in the lighthouse (one writer, already serialized
// under its mutex); the read path is the pure fold below, exposed through
// the C API as tft_history_replay and mirrored line-for-line by
// torchft_tpu/tracing.py:history_fold (native<->Python parity is pinned by
// test, the same convention as the healthwatch replay hooks).
#pragma once

#include <cstdint>
#include <deque>
#include <fstream>
#include <mutex>
#include <string>
#include <vector>

#include "json.h"

namespace tft {

class HistoryStore {
 public:
  // Empty path = disabled (every append is a no-op). The file is opened in
  // append mode so a restarted lighthouse extends the same history.
  explicit HistoryStore(std::string path);

  bool enabled() const { return !path_.empty(); }
  const std::string& path() const { return path_; }

  // Live policy stream: an optional bounded in-memory ring that records
  // the same stamped events the file would, so an in-process consumer
  // (the policy engine) can fold them without a file round-trip. A store
  // is "recording" when either sink is active; the ring works with an
  // empty path (telemetry-only deployments) and alongside one.
  void enable_ring(int64_t cap);
  bool ring_enabled() const;
  bool recording() const { return enabled() || ring_enabled(); }

  // Drain (move out) the ring contents accumulated since the last drain.
  std::vector<Json> drain_ring();

  // Append one event line. The event must carry a "kind" field; the store
  // stamps "seq" (monotonic per store) and "ts_ms" (epoch millis). IO
  // errors are swallowed: history must never take down the control plane.
  void append(Json event);

  int64_t events_written() const;

 private:
  std::string path_;
  mutable std::mutex mu_;
  std::ofstream out_;
  int64_t seq_ = 0;
  int64_t ring_cap_ = 0;  // 0 = ring disabled
  int64_t ring_dropped_ = 0;
  std::deque<Json> ring_;
};

// Pure fold over a history event array -> deterministic summary. Mirrored
// exactly by torchft_tpu.tracing.history_fold; change both together.
Json history_fold(const Json& events);

}  // namespace tft
