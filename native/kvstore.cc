#include "kvstore.h"

namespace tft {

KvStoreServer::KvStoreServer(const std::string& bind) {
  server_ = std::make_unique<RpcServer>(
      bind, [this](const std::string& m, const Json& p, TimePoint d) {
        return handle(m, p, d);
      });
}

KvStoreServer::~KvStoreServer() { shutdown(); }

void KvStoreServer::shutdown() {
  bool was = running_.exchange(false);
  if (!was) return;
  cv_.notify_all();
  server_->shutdown();
}

Json KvStoreServer::handle(const std::string& method, const Json& params,
                           TimePoint deadline) {
  if (method == "set") {
    std::lock_guard<std::mutex> lk(mu_);
    data_[params.get("key").as_string()] = params.get("value").as_string();
    cv_.notify_all();
    return Json::object();
  }
  if (method == "get") {
    std::string key = params.get("key").as_string();
    bool wait = params.get_or("wait", Json(true)).as_bool();
    std::unique_lock<std::mutex> lk(mu_);
    if (!wait) {
      auto it = data_.find(key);
      if (it == data_.end()) throw RpcError("not_found", "key not set: " + key);
      Json j = Json::object();
      j["value"] = it->second;
      return j;
    }
    bool got = cv_.wait_until(lk, deadline, [&] {
      return !running_.load() || data_.count(key) > 0;
    });
    if (!running_.load()) throw RpcError("unavailable", "store shutting down");
    if (!got) throw TimeoutError("get timed out waiting for key: " + key);
    Json j = Json::object();
    j["value"] = data_[key];
    return j;
  }
  if (method == "add") {
    std::string key = params.get("key").as_string();
    int64_t amount = params.get("amount").as_int();
    std::lock_guard<std::mutex> lk(mu_);
    int64_t cur = 0;
    auto it = data_.find(key);
    if (it != data_.end()) {
      try {
        size_t used = 0;
        cur = std::stoll(it->second, &used);
        if (used != it->second.size())
          throw std::invalid_argument("trailing");
      } catch (const std::exception&) {
        // a clear error beats an opaque stoll crash: set() and add() share
        // one namespace but their value formats do not mix
        throw RpcError("invalid",
                       "add on key '" + key + "' whose value is not a "
                       "counter (was it written by set()?)");
      }
    }
    cur += amount;
    data_[key] = std::to_string(cur);
    cv_.notify_all();
    Json j = Json::object();
    j["value"] = cur;
    return j;
  }
  if (method == "check") {
    std::lock_guard<std::mutex> lk(mu_);
    bool all = true;
    for (const auto& k : params.get("keys").as_array())
      if (!data_.count(k.as_string())) { all = false; break; }
    Json j = Json::object();
    j["exists"] = all;
    return j;
  }
  if (method == "delete") {
    std::lock_guard<std::mutex> lk(mu_);
    size_t n = data_.erase(params.get("key").as_string());
    Json j = Json::object();
    j["deleted"] = n > 0;
    return j;
  }
  if (method == "num_keys") {
    std::lock_guard<std::mutex> lk(mu_);
    Json j = Json::object();
    j["count"] = static_cast<int64_t>(data_.size());
    return j;
  }
  throw RpcError("invalid", "unknown kvstore method: " + method);
}

}  // namespace tft
