#include "wire.h"

#include <cstring>

namespace tft {
namespace {

constexpr size_t kMaxFrame = 512ull << 20;  // 512 MiB hard cap

void write_frame(Socket& sock, const std::string& payload, TimePoint deadline) {
  if (payload.size() > kMaxFrame) throw std::runtime_error("frame too large");
  uint8_t hdr[4] = {
      static_cast<uint8_t>((payload.size() >> 24) & 0xFF),
      static_cast<uint8_t>((payload.size() >> 16) & 0xFF),
      static_cast<uint8_t>((payload.size() >> 8) & 0xFF),
      static_cast<uint8_t>(payload.size() & 0xFF),
  };
  sock.send_all(hdr, 4, deadline);
  sock.send_all(payload.data(), payload.size(), deadline);
}

std::string read_frame(Socket& sock, TimePoint deadline) {
  uint8_t hdr[4];
  sock.recv_all(hdr, 4, deadline);
  size_t len = (static_cast<size_t>(hdr[0]) << 24) |
               (static_cast<size_t>(hdr[1]) << 16) |
               (static_cast<size_t>(hdr[2]) << 8) | static_cast<size_t>(hdr[3]);
  if (len > kMaxFrame) throw std::runtime_error("frame too large");
  std::string payload(len, '\0');
  if (len > 0) sock.recv_all(payload.data(), len, deadline);
  return payload;
}

}  // namespace

RpcServer::RpcServer(const std::string& bind, Handler handler, HttpHandler http)
    : listener_(std::make_unique<Listener>(bind)),
      handler_(std::move(handler)),
      http_(std::move(http)) {
  accept_thread_ = std::thread([this] { accept_loop(); });
}

RpcServer::~RpcServer() { shutdown(); }

void RpcServer::shutdown() {
  bool was_running = running_.exchange(false);
  if (!was_running) return;
  listener_->shutdown();
  {
    std::lock_guard<std::mutex> lk(conn_mu_);
    // shutdown (not close) from this thread: it wakes any blocked recv/send
    // in the conn thread, which then exits and closes its own fd. Closing
    // here would race the conn thread's use of the fd number — a freed fd
    // can be reallocated to an unrelated file and corrupted.
    for (auto& c : conns_) c->shutdown_rdwr();
  }
  if (accept_thread_.joinable()) accept_thread_.join();
  std::vector<std::unique_ptr<ConnSlot>> slots;
  {
    std::lock_guard<std::mutex> lk(conn_mu_);
    slots.swap(conn_slots_);
  }
  for (auto& s : slots)
    if (s->thread.joinable()) s->thread.join();
}

void RpcServer::reap_finished_locked() {
  auto it = conn_slots_.begin();
  while (it != conn_slots_.end()) {
    if ((*it)->done.load()) {
      if ((*it)->thread.joinable()) (*it)->thread.join();
      it = conn_slots_.erase(it);
    } else {
      ++it;
    }
  }
}

void RpcServer::accept_loop() {
  while (running_.load()) {
    std::optional<Socket> sock;
    try {
      sock = listener_->accept(Millis(200));
    } catch (const std::exception&) {
      if (!running_.load()) return;
      continue;
    }
    std::lock_guard<std::mutex> lk(conn_mu_);
    reap_finished_locked();
    if (!sock) continue;
    if (!running_.load()) return;
    auto sp = std::make_shared<Socket>(std::move(*sock));
    conns_.insert(sp);
    auto slot = std::make_unique<ConnSlot>();
    ConnSlot* slot_ptr = slot.get();
    slot_ptr->thread = std::thread([this, sp, slot_ptr] {
      serve_conn(sp);
      {
        std::lock_guard<std::mutex> lk2(conn_mu_);
        conns_.erase(sp);
      }
      slot_ptr->done.store(true);
    });
    conn_slots_.push_back(std::move(slot));
  }
}

void RpcServer::serve_conn(std::shared_ptr<Socket> sock) {
  try {
    // Sniff: HTTP request lines start with an ASCII method ("GET ", "POST",
    // "HEAD"); our frames start with a 4-byte length whose first byte is
    // 0x00 for any sane payload (<16 MiB). A single peek can return fewer
    // than 4 bytes under TCP segmentation, so keep peeking until we have
    // them (the level-triggered wait inside peek() returns immediately while
    // data is pending, hence the tiny sleep between retries).
    char probe[4] = {0};
    TimePoint sniff_deadline = Clock::now() + Millis(30000);
    size_t n = 0;
    while (n < 4) {
      if (Clock::now() >= sniff_deadline)
        throw std::runtime_error("sniff timed out");
      n = sock->peek(probe, 4, sniff_deadline);
      if (n < 4) std::this_thread::sleep_for(std::chrono::milliseconds(2));
    }
    bool is_http = memcmp(probe, "GET ", 4) == 0 ||
                   memcmp(probe, "POST", 4) == 0 ||
                   memcmp(probe, "HEAD", 4) == 0;
    if (is_http) {
      serve_http(*sock, "");
      return;
    }
    while (running_.load()) {
      // Idle keep-alive: wait up to 1h for the next request frame.
      std::string req_text = read_frame(*sock, Clock::now() + Millis(3600000));
      Json resp = Json::object();
      try {
        Json req = Json::parse(req_text);
        std::string method = req.get("method").as_string();
        {
          std::lock_guard<std::mutex> lk(rx_mu_);
          RxStat& s = rx_stats_[method];
          s.bytes += req_text.size() + 4;  // payload + length header
          s.calls += 1;
        }
        int64_t timeout_ms = req.get_or("timeout_ms", Json(int64_t{60000})).as_int();
        Json params = req.get_or("params", Json::object());
        Json result = handler_(method, params, deadline_from_ms(timeout_ms));
        resp["ok"] = true;
        resp["result"] = result;
      } catch (const RpcError& e) {
        resp["ok"] = false;
        resp["code"] = e.code;
        resp["error"] = std::string(e.what());
      } catch (const std::exception& e) {
        resp["ok"] = false;
        std::string msg = e.what();
        resp["code"] = msg.find("timed out") != std::string::npos
                           ? std::string("timeout")
                           : std::string("internal");
        resp["error"] = msg;
      }
      write_frame(*sock, resp.dump(), Clock::now() + Millis(60000));
    }
  } catch (const std::exception&) {
    // connection closed / timed out: drop it
  }
}

Json RpcServer::rx_stats() const {
  std::lock_guard<std::mutex> lk(rx_mu_);
  Json out = Json::object();
  for (const auto& [method, s] : rx_stats_) {
    Json entry = Json::object();
    entry["bytes"] = static_cast<int64_t>(s.bytes);
    entry["calls"] = static_cast<int64_t>(s.calls);
    out[method] = entry;
  }
  return out;
}

void RpcServer::serve_http(Socket& sock, const std::string&) {
  try {
    // Read until end of headers (tiny requests only; dashboards).
    std::string buf;
    char c;
    TimePoint deadline = Clock::now() + Millis(10000);
    while (buf.find("\r\n\r\n") == std::string::npos && buf.size() < 16384) {
      sock.recv_all(&c, 1, deadline);
      buf.push_back(c);
    }
    auto line_end = buf.find("\r\n");
    std::string line = buf.substr(0, line_end);
    auto sp1 = line.find(' ');
    auto sp2 = line.rfind(' ');
    std::string method = line.substr(0, sp1);
    std::string path =
        sp2 > sp1 ? line.substr(sp1 + 1, sp2 - sp1 - 1) : std::string("/");
    std::string status = "404 Not Found", ctype = "text/plain", body = "not found";
    if (http_) std::tie(status, ctype, body) = http_(method, path);
    std::string resp = "HTTP/1.1 " + status +
                       "\r\nContent-Type: " + ctype +
                       "\r\nContent-Length: " + std::to_string(body.size()) +
                       "\r\nConnection: close\r\n\r\n" + body;
    sock.send_all(resp.data(), resp.size(), Clock::now() + Millis(10000));
  } catch (const std::exception&) {
  }
}

RpcClient::RpcClient(std::string addr, Millis connect_timeout)
    : addr_(std::move(addr)), connect_timeout_(connect_timeout) {}

Socket RpcClient::dial(Millis timeout) {
  auto [host, port] = split_host_port(addr_);
  TimePoint connect_deadline = Clock::now() + std::min(connect_timeout_, timeout);
  return connect_with_retry(host, port, connect_deadline);
}

Json RpcClient::call_on(Socket& sock, const std::string& method,
                        const Json& params, Millis timeout) {
  // Full-call deadline: the handler may legitimately block for the entire
  // timeout (quorum waits); allow a small grace for the response to arrive.
  TimePoint deadline = Clock::now() + timeout + Millis(2000);
  Json req = Json::object();
  req["method"] = method;
  req["params"] = params;
  req["timeout_ms"] =
      static_cast<int64_t>(std::chrono::duration_cast<Millis>(timeout).count());
  write_frame(sock, req.dump(), deadline);
  std::string resp_text = read_frame(sock, deadline);
  Json resp = Json::parse(resp_text);
  if (resp.get("ok").as_bool()) return resp.get_or("result", Json());
  std::string code = resp.get_or("code", Json("internal")).as_string();
  std::string err = resp.get_or("error", Json("unknown")).as_string();
  if (code == "timeout") throw TimeoutError(err);
  throw RpcError(code, err);
}

Json RpcClient::call(const std::string& method, const Json& params,
                     Millis timeout) {
  try {
    std::unique_lock<std::mutex> lk(mu_, std::try_to_lock);
    if (!lk.owns_lock()) {
      // Cached connection busy with a (possibly long-blocking) call from
      // another thread: use a one-shot connection so we never queue behind it.
      Socket sock = dial(timeout);
      return call_on(sock, method, params, timeout);
    }
    bool reused = cached_.valid();
    if (!reused) cached_ = dial(timeout);
    try {
      return call_on(cached_, method, params, timeout);
    } catch (const RpcError&) {
      throw;  // server replied; connection is fine
    } catch (const std::exception& e) {
      cached_.close();
      bool timed_out =
          std::string(e.what()).find("timed out") != std::string::npos;
      // Reconnect-and-retry only a *stale* cached connection (closed/reset by
      // a restarted or idle-timing-out server). Timeouts and fresh-connection
      // failures don't retry — the request may already have been processed.
      // Only KNOWN-idempotent methods retry (whitelist fails safe; a
      // blacklist fails open for future mutating RPCs): a reply lost after
      // the server applied the request re-executes it — "add" would
      // double-increment rendezvous counters, and "should_commit" would
      // reset a decided vote round into a divergent 2PC outcome.
      // NB: "quorum" is NOT idempotent — the manager's barrier counts
      // joins, and a re-executed join after a lost reply would offset
      // every subsequent round by one. (The manager->lighthouse quorum
      // call has its own application-level retry loop instead.)
      bool idempotent = method == "get" || method == "wait" ||
                        method == "heartbeat" ||
                        method == "checkpoint_metadata" ||
                        method == "status" || method == "set" ||
                        method == "kill";
      if (!reused || timed_out || !idempotent) throw;
      cached_ = dial(timeout);
      return call_on(cached_, method, params, timeout);
    }
  } catch (const RpcError&) {
    throw;
  } catch (const std::exception& e) {
    std::string msg = std::string(e.what());
    if (msg.find("timed out") != std::string::npos)
      throw TimeoutError(method + " to " + addr_ + ": " + msg);
    throw RpcError("unavailable", method + " to " + addr_ + ": " + msg);
  }
}

}  // namespace tft
