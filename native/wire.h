// Length-framed JSON RPC over TCP, with HTTP GET multiplexed on the same
// listener (the reference multiplexes an axum HTTP dashboard and tonic gRPC
// on one port, src/lighthouse.rs:362-400; we sniff the first bytes instead).
//
// Frame: 4-byte big-endian payload length + UTF-8 JSON.
// Request  : {"method": str, "params": {...}, "timeout_ms": int}
// Response : {"ok": true, "result": ...} | {"ok": false, "code": str, "error": str}
// Codes: "timeout", "not_found", "invalid", "internal", "unavailable".
#pragma once

#include <atomic>
#include <functional>
#include <map>
#include <memory>
#include <mutex>
#include <set>
#include <string>
#include <thread>
#include <vector>

#include "json.h"
#include "net.h"

namespace tft {

// Thrown by handlers/clients to signal a typed RPC error.
struct RpcError : std::runtime_error {
  RpcError(std::string code_, const std::string& msg)
      : std::runtime_error(msg), code(std::move(code_)) {}
  std::string code;
};

struct TimeoutError : RpcError {
  explicit TimeoutError(const std::string& msg) : RpcError("timeout", msg) {}
};

class RpcServer {
 public:
  using Handler =
      std::function<Json(const std::string& method, const Json& params,
                         TimePoint deadline)>;
  // Returns (status_line_suffix e.g. "200 OK", content_type, body).
  using HttpHandler = std::function<std::tuple<std::string, std::string, std::string>(
      const std::string& method, const std::string& path)>;

  RpcServer(const std::string& bind, Handler handler, HttpHandler http = nullptr);
  ~RpcServer();

  int port() const { return listener_->port(); }
  void shutdown();

  // Per-method receive accounting: frame bytes (4-byte header + payload)
  // and call counts, keyed by RPC method. This is what the fleet bench
  // reads to measure heartbeat fan-in bytes at the root lighthouse.
  // Returns {"<method>": {"bytes": N, "calls": N}, ...}.
  Json rx_stats() const;

 private:
  void accept_loop();
  void serve_conn(std::shared_ptr<Socket> sock);
  void serve_http(Socket& sock, const std::string& deadline_hint);

  std::unique_ptr<Listener> listener_;
  Handler handler_;
  HttpHandler http_;
  std::atomic<bool> running_{true};
  std::thread accept_thread_;
  std::mutex conn_mu_;
  std::set<std::shared_ptr<Socket>> conns_;
  // One slot per live connection; finished slots are reaped (joined) by the
  // accept loop so long-running servers don't accumulate dead threads.
  struct ConnSlot {
    std::thread thread;
    std::atomic<bool> done{false};
  };
  std::vector<std::unique_ptr<ConnSlot>> conn_slots_;
  void reap_finished_locked();

  struct RxStat {
    uint64_t bytes = 0;
    uint64_t calls = 0;
  };
  mutable std::mutex rx_mu_;
  std::map<std::string, RxStat> rx_stats_;
};

// Framed-JSON RPC client with a cached keep-alive connection.
// The cached socket is reused across calls (reconnecting once if it went
// stale — the reference's reconnect-on-failure behavior,
// src/manager.rs:250-306). If another thread currently holds the cached
// connection, the call transparently uses a one-shot connection instead, so
// a long-blocking quorum call never delays concurrent heartbeats.
class RpcClient {
 public:
  // addr: "host:port" (scheme prefixes tolerated).
  RpcClient(std::string addr, Millis connect_timeout);

  // Throws TimeoutError / RpcError / std::runtime_error.
  Json call(const std::string& method, const Json& params, Millis timeout);

  const std::string& addr() const { return addr_; }

 private:
  Json call_on(Socket& sock, const std::string& method, const Json& params,
               Millis timeout);
  Socket dial(Millis timeout);

  std::string addr_;
  Millis connect_timeout_;
  std::mutex mu_;       // guards cached_
  Socket cached_;       // invalid until first call
};

}  // namespace tft
