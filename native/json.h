// Minimal JSON value type with parse/serialize for the torchft_tpu control
// plane wire protocol. The reference control plane speaks protobuf over gRPC
// (reference: proto/torchft.proto); this build has no C++ gRPC toolchain, so
// the C++ servers speak length-framed JSON over TCP instead — same message
// semantics, different encoding.
#pragma once

#include <cstdint>
#include <map>
#include <memory>
#include <stdexcept>
#include <string>
#include <vector>

namespace tft {

class Json;
using JsonArray = std::vector<Json>;
// std::map keeps object keys ordered -> deterministic serialization.
using JsonObject = std::map<std::string, Json>;

class Json {
 public:
  enum class Type { Null, Bool, Int, Double, String, Array, Object };

  Json() : type_(Type::Null) {}
  Json(std::nullptr_t) : type_(Type::Null) {}
  Json(bool b) : type_(Type::Bool), bool_(b) {}
  Json(int v) : type_(Type::Int), int_(v) {}
  Json(int64_t v) : type_(Type::Int), int_(v) {}
  Json(uint64_t v) : type_(Type::Int), int_(static_cast<int64_t>(v)) {}
  Json(double v) : type_(Type::Double), double_(v) {}
  Json(const char* s) : type_(Type::String), str_(s) {}
  Json(std::string s) : type_(Type::String), str_(std::move(s)) {}
  Json(JsonArray a) : type_(Type::Array), arr_(std::move(a)) {}
  Json(JsonObject o) : type_(Type::Object), obj_(std::move(o)) {}

  static Json object() { return Json(JsonObject{}); }
  static Json array() { return Json(JsonArray{}); }

  Type type() const { return type_; }
  bool is_null() const { return type_ == Type::Null; }
  bool is_object() const { return type_ == Type::Object; }
  bool is_array() const { return type_ == Type::Array; }
  bool is_string() const { return type_ == Type::String; }
  bool is_number() const { return type_ == Type::Int || type_ == Type::Double; }
  bool is_bool() const { return type_ == Type::Bool; }

  bool as_bool() const { check(Type::Bool); return bool_; }
  int64_t as_int() const {
    if (type_ == Type::Double) return static_cast<int64_t>(double_);
    check(Type::Int);
    return int_;
  }
  double as_double() const {
    if (type_ == Type::Int) return static_cast<double>(int_);
    check(Type::Double);
    return double_;
  }
  const std::string& as_string() const { check(Type::String); return str_; }
  const JsonArray& as_array() const { check(Type::Array); return arr_; }
  JsonArray& as_array() { check(Type::Array); return arr_; }
  const JsonObject& as_object() const { check(Type::Object); return obj_; }
  JsonObject& as_object() { check(Type::Object); return obj_; }

  // Object access. operator[] inserts (object must be mutable); get() is safe.
  Json& operator[](const std::string& key) {
    if (type_ == Type::Null) { type_ = Type::Object; }
    check(Type::Object);
    return obj_[key];
  }
  bool contains(const std::string& key) const {
    return type_ == Type::Object && obj_.count(key) > 0;
  }
  const Json& get(const std::string& key) const {
    check(Type::Object);
    auto it = obj_.find(key);
    if (it == obj_.end()) throw std::runtime_error("missing json key: " + key);
    return it->second;
  }
  Json get_or(const std::string& key, Json def) const {
    if (!contains(key)) return def;
    return obj_.at(key);
  }
  void push_back(Json v) {
    if (type_ == Type::Null) { type_ = Type::Array; }
    check(Type::Array);
    arr_.push_back(std::move(v));
  }
  size_t size() const {
    if (type_ == Type::Array) return arr_.size();
    if (type_ == Type::Object) return obj_.size();
    throw std::runtime_error("json: size() on non-container");
  }

  std::string dump() const;
  static Json parse(const std::string& text);

 private:
  void check(Type t) const {
    if (type_ != t) throw std::runtime_error("json: wrong type access");
  }

  Type type_;
  bool bool_ = false;
  int64_t int_ = 0;
  double double_ = 0.0;
  std::string str_;
  JsonArray arr_;
  JsonObject obj_;
};

}  // namespace tft
