// Healthwatch: per-replica health ledger for the lighthouse.
//
// Quorum health was binary (heartbeat fresh or stale, quorum.cc:71-76); a
// slow-but-alive replica drags every synchronous step because the managed
// allreduce is a barrier across the quorum. The ledger keeps a rolling
// window of per-step compute-time samples per replica (reported as optional
// telemetry on the existing heartbeat), scores each replica against the
// quorum median (modified z-score: median + MAD, with a relative floor on
// the scale because MAD degenerates to zero on a homogeneous fleet), and
// runs the escalation policy:
//
//   ok -> warn          score > warn_z (event: straggler_warn)
//   warn -> ejected     score > eject_z for eject_steps consecutive samples,
//                       mode == "eject" only, never below min_replicas
//                       (event: eject; replica enters the exclusion set the
//                       quorum computation consults)
//   ejected -> probation  probation_ms of continuous fresh heartbeats
//                       (event: readmit; replica leaves the exclusion set)
//   probation -> ok     probe_ok clean samples; one sample over eject_z
//                       re-ejects immediately
//   ok/warn -> degraded telemetry reports group_world_size below
//                       full_group_world_size: the replica lost a chip and
//                       reshard onto survivors (event: degrade). Samples
//                       are capacity-scaled, strikes never accrue, and the
//                       state returns to ok once full degree is reported
//                       again (event: restore).
//
// In "observe" mode (the default) the ledger scores and reports but never
// ejects, so existing jobs see zero behavior change. The scoring math is
// mirrored by torchft_tpu/healthwatch.py (the canonical spec) and parity
// tested through the capi replay hooks.
#pragma once

#include <deque>
#include <map>
#include <set>
#include <string>
#include <vector>

#include "json.h"
#include "net.h"

namespace tft {

struct HealthOpts {
  std::string mode = "observe";  // "off" | "observe" | "eject"
  int64_t window = 32;           // samples kept per replica
  int64_t min_samples = 5;       // warmup: score only with this many samples
  double warn_z = 3.0;           // score above this -> warn
  double eject_z = 6.0;          // score above this counts an eject strike
  int64_t eject_steps = 3;       // consecutive strikes before ejection
  int64_t probation_ms = 10000;  // continuous fresh beats before readmission
  int64_t probe_ok = 3;          // clean samples in probation before ok
  double rel_floor = 0.05;       // scale floor as a fraction of the median

  static HealthOpts from_json(const Json& j);
  Json to_json() const;
};

// kDegraded is appended (not renumbered): codes 0..3 are pinned by the
// Python parity tests, Manager timings(), and the /metrics docs.
enum class HealthState {
  kOk = 0,
  kWarn = 1,
  kEjected = 2,
  kProbation = 3,
  kDegraded = 4,
};
const char* health_state_name(HealthState s);

// Pure scoring: per-replica straggler score from rolling windows of
// compute-time samples. Replicas with fewer than min_samples samples are
// not scored (warmup grace) and do not contribute to the quorum median.
// Fewer than 2 scorable replicas -> all zeros (no peer group to compare).
std::map<std::string, double> straggler_scores(
    const std::map<std::string, std::vector<double>>& windows,
    const HealthOpts& opts);

struct ReplicaHealth {
  std::deque<double> window;  // compute-time samples (step_s - wire_s)
  int64_t last_step = -1;     // dedup: one sample per reported step
  double last_step_s = 0.0;
  double last_wire_s = 0.0;
  double score = 0.0;
  HealthState state = HealthState::kOk;
  int64_t strikes = 0;    // consecutive samples over eject_z
  int64_t probes_ok = 0;  // clean samples while in probation
  int64_t ejections = 0;
  int64_t readmissions = 0;
  int64_t samples_total = 0;
  TimePoint ejected_at{};
  TimePoint last_beat{};
  // Degrade plane: last reported group degree (0 = never reported).
  int64_t group_world_size = 0;
  int64_t full_group_world_size = 0;
};

class HealthLedger {
 public:
  HealthLedger(HealthOpts opts, int64_t heartbeat_timeout_ms,
               int64_t min_replicas);

  const HealthOpts& opts() const { return opts_; }
  // Live retune (policy plane): thresholds apply from the next evaluate;
  // existing window samples, strikes and probation clocks are preserved.
  // Caller holds the lighthouse mutex (same discipline as on_heartbeat).
  void set_opts(HealthOpts opts) { opts_ = std::move(opts); }

  // Feed one heartbeat; telemetry may be null (plain beat). Returns the
  // policy events this beat produced ({"kind": "straggler_warn" | "eject" |
  // "readmit", "replica_id": ..., ...}).
  std::vector<Json> on_heartbeat(const std::string& rid, const Json* telemetry,
                                 TimePoint now);

  // Periodic evaluation: probation transitions (time-based) and pruning of
  // long-dead replicas (same horizon the lighthouse uses for heartbeats).
  std::vector<Json> tick(TimePoint now, int64_t prune_after_ms);

  const std::set<std::string>& exclusions() const { return excluded_; }

  // Per-replica summary returned in the heartbeat response (so the Manager
  // can surface health_state / ejections / readmissions in timings()).
  Json replica_json(const std::string& rid) const;

  // Full ledger dump for the /health endpoint.
  Json to_json(TimePoint now) const;

 private:
  // Recompute every replica's score from current windows; run the policy
  // for `rid` (the replica that just delivered a new sample).
  void evaluate(const std::string& rid, TimePoint now,
                std::vector<Json>* events);
  bool can_eject(TimePoint now) const;
  void eject(const std::string& rid, ReplicaHealth& rh, TimePoint now,
             std::vector<Json>* events);
  void remember(const std::vector<Json>& events);

  HealthOpts opts_;
  int64_t heartbeat_timeout_ms_;
  int64_t min_replicas_;
  std::map<std::string, ReplicaHealth> replicas_;
  std::set<std::string> excluded_;
  std::deque<Json> recent_events_;  // bounded tail for /health
};

}  // namespace tft
