// Lighthouse: the global quorum coordinator, one per job.
// Equivalent of the reference's Rust Lighthouse (src/lighthouse.rs:68-413):
// collects heartbeats and quorum requests from every replica-group manager,
// computes quorums on a periodic tick, broadcasts results to blocked quorum
// RPCs (with the re-subscribe loop for members missing from a quorum), and
// serves an HTML/JSON status dashboard with per-replica kill on the same port.
#pragma once

#include <condition_variable>
#include <memory>
#include <mutex>
#include <thread>

#include "healthwatch.h"
#include "history.h"
#include "quorum.h"
#include "wire.h"

namespace tft {

class Lighthouse {
 public:
  Lighthouse(const std::string& bind, LighthouseOpts opts,
             HealthOpts health = HealthOpts{});
  ~Lighthouse();

  int port() const { return server_->port(); }
  std::string address() const;
  void shutdown();

  // ---- policy plane (in-process control surface; NOT wire RPCs) ----
  // Install/replace the versioned policy frame {policy_seq, mode,
  // knob_overrides} piggybacked on every heartbeat / agg_tick reply.
  // An empty object clears the frame (kill switch).
  void set_policy(const Json& frame);
  // Current policy frame ("{}" when none is set).
  std::string policy_json();
  // Drain the live history ring (enable via LighthouseOpts::policy_ring)
  // as a JSON array — the policy engine's live event feed.
  std::string drain_events();
  // Live-retune the health ledger thresholds (partial HealthOpts JSON
  // merged over the current opts). Returns the resulting opts as JSON.
  std::string retune_health(const Json& partial);

 private:
  Json handle(const std::string& method, const Json& params, TimePoint deadline);
  std::tuple<std::string, std::string, std::string> handle_http(
      const std::string& method, const std::string& path);

  Json rpc_quorum(const Json& params, TimePoint deadline);
  Json rpc_heartbeat(const Json& params);
  // Batched pod delta from a lighthouse aggregator (aggregator.h): applies
  // the pod's live set + telemetry deltas + quorum joiners in one RPC and
  // returns quorum/health fan-back. Rejects stale (epoch, seq) frames from
  // a previous aggregator incarnation.
  Json rpc_agg_tick(const Json& params);
  Json status_json();
  Json health_json();
  std::string status_html();
  // Prometheus text exposition (served at /metrics beside /health).
  std::string metrics_text();
  // Must hold mu_. Log + sync ledger exclusions into the quorum state.
  void apply_health_events_locked(const std::vector<Json>& events);
  // Must hold mu_. Shared beat path for direct heartbeats and aggregator
  // batches: heartbeat timestamp + health ledger + history telemetry dedup.
  void apply_beat_locked(const std::string& replica_id, const Json* telemetry,
                         TimePoint now);
  // Must hold mu_. Address of a live aggregator (freshest tick), or "".
  std::string pick_aggregator_locked(TimePoint now) const;

  void tick_loop();
  // Must hold mu_. Runs one quorum computation; publishes on success.
  void quorum_tick_locked();

  LighthouseOpts opts_;
  std::mutex mu_;
  std::condition_variable quorum_cv_;
  LighthouseState state_;
  HealthLedger ledger_;  // guarded by mu_
  HistoryStore history_;  // internally locked; appended under mu_
  // Per-replica last telemetry step recorded to history (dedup: a re-sent
  // beat payload for the same step writes nothing). Guarded by mu_.
  std::map<std::string, int64_t> history_telemetry_step_;
  // Aggregator registry: one entry per aggregator incarnation, used for
  // stale-delta rejection, beats_same expansion, and replacement naming
  // when a direct-mode manager asks for an aggregator. Guarded by mu_.
  struct AggregatorInfo {
    std::string addr;
    int64_t epoch = 0;
    int64_t last_seq = 0;
    TimePoint last_tick{};
    std::set<std::string> live;  // last full live set received
    bool has_live = false;       // full set seen this incarnation
    uint64_t ticks = 0;
  };
  std::map<std::string, AggregatorInfo> aggregators_;
  // Broadcast channel: bump generation + store latest quorum.
  uint64_t quorum_gen_ = 0;
  std::optional<QuorumSnapshot> latest_quorum_;
  std::string last_reason_;  // dedup logging (reference ChangeLogger)
  // Latest policy frame (set_policy). Type::Null until first set; carried
  // as an optional "policy" key on heartbeat / agg_tick replies so the
  // distribution rides the existing wire with zero new RPC methods.
  Json policy_frame_;

  std::atomic<bool> running_{true};
  std::unique_ptr<RpcServer> server_;
  std::thread tick_thread_;
};

}  // namespace tft
