// Lighthouse: the global quorum coordinator, one per job.
// Equivalent of the reference's Rust Lighthouse (src/lighthouse.rs:68-413):
// collects heartbeats and quorum requests from every replica-group manager,
// computes quorums on a periodic tick, broadcasts results to blocked quorum
// RPCs (with the re-subscribe loop for members missing from a quorum), and
// serves an HTML/JSON status dashboard with per-replica kill on the same port.
#pragma once

#include <condition_variable>
#include <memory>
#include <mutex>
#include <thread>

#include "healthwatch.h"
#include "history.h"
#include "quorum.h"
#include "wire.h"

namespace tft {

class Lighthouse {
 public:
  Lighthouse(const std::string& bind, LighthouseOpts opts,
             HealthOpts health = HealthOpts{});
  ~Lighthouse();

  int port() const { return server_->port(); }
  std::string address() const;
  void shutdown();

 private:
  Json handle(const std::string& method, const Json& params, TimePoint deadline);
  std::tuple<std::string, std::string, std::string> handle_http(
      const std::string& method, const std::string& path);

  Json rpc_quorum(const Json& params, TimePoint deadline);
  Json rpc_heartbeat(const Json& params);
  Json status_json();
  Json health_json();
  std::string status_html();
  // Prometheus text exposition (served at /metrics beside /health).
  std::string metrics_text();
  // Must hold mu_. Log + sync ledger exclusions into the quorum state.
  void apply_health_events_locked(const std::vector<Json>& events);

  void tick_loop();
  // Must hold mu_. Runs one quorum computation; publishes on success.
  void quorum_tick_locked();

  LighthouseOpts opts_;
  std::mutex mu_;
  std::condition_variable quorum_cv_;
  LighthouseState state_;
  HealthLedger ledger_;  // guarded by mu_
  HistoryStore history_;  // internally locked; appended under mu_
  // Per-replica last telemetry step recorded to history (dedup: a re-sent
  // beat payload for the same step writes nothing). Guarded by mu_.
  std::map<std::string, int64_t> history_telemetry_step_;
  // Broadcast channel: bump generation + store latest quorum.
  uint64_t quorum_gen_ = 0;
  std::optional<QuorumSnapshot> latest_quorum_;
  std::string last_reason_;  // dedup logging (reference ChangeLogger)

  std::atomic<bool> running_{true};
  std::unique_ptr<RpcServer> server_;
  std::thread tick_thread_;
};

}  // namespace tft
