#include "net.h"

#include <arpa/inet.h>
#include <errno.h>
#include <fcntl.h>
#include <netdb.h>
#include <netinet/in.h>
#include <netinet/tcp.h>
#include <poll.h>
#include <string.h>
#include <sys/socket.h>
#include <unistd.h>

#include <algorithm>
#include <stdexcept>
#include <thread>

namespace tft {

int64_t ms_until(TimePoint deadline) {
  auto d = std::chrono::duration_cast<Millis>(deadline - Clock::now()).count();
  return d;
}

namespace {

[[noreturn]] void throw_errno(const std::string& what) {
  throw std::runtime_error(what + ": " + strerror(errno));
}

[[noreturn]] void throw_timeout(const std::string& what) {
  throw std::runtime_error(what + ": timed out");
}

void set_nonblocking(int fd, bool nb) {
  int flags = fcntl(fd, F_GETFL, 0);
  if (flags < 0) throw_errno("fcntl");
  if (nb) flags |= O_NONBLOCK; else flags &= ~O_NONBLOCK;
  if (fcntl(fd, F_SETFL, flags) < 0) throw_errno("fcntl");
}

// Wait for readability/writability up to deadline. events: POLLIN/POLLOUT.
bool wait_fd(int fd, short events, TimePoint deadline) {
  while (true) {
    int64_t ms = ms_until(deadline);
    if (ms <= 0) return false;
    struct pollfd pfd{fd, events, 0};
    int rc = poll(&pfd, 1, static_cast<int>(std::min<int64_t>(ms, 1000)));
    if (rc < 0) {
      if (errno == EINTR) continue;
      throw_errno("poll");
    }
    if (rc > 0) return true;
  }
}

}  // namespace

Socket& Socket::operator=(Socket&& o) noexcept {
  if (this != &o) {
    close();
    fd_ = o.fd_;
    o.fd_ = -1;
  }
  return *this;
}

Socket::~Socket() { close(); }

void Socket::shutdown_rdwr() {
  if (fd_ >= 0) ::shutdown(fd_, SHUT_RDWR);
}

void Socket::close() {
  if (fd_ >= 0) {
    ::close(fd_);
    fd_ = -1;
  }
}

void Socket::send_all(const void* data, size_t len, TimePoint deadline) {
  const char* p = static_cast<const char*>(data);
  size_t sent = 0;
  while (sent < len) {
    ssize_t n = ::send(fd_, p + sent, len - sent, MSG_NOSIGNAL | MSG_DONTWAIT);
    if (n > 0) {
      sent += static_cast<size_t>(n);
      continue;
    }
    if (n < 0 && errno != EAGAIN && errno != EWOULDBLOCK && errno != EINTR)
      throw_errno("send");
    if (!wait_fd(fd_, POLLOUT, deadline)) throw_timeout("send");
  }
}

void Socket::recv_all(void* data, size_t len, TimePoint deadline) {
  char* p = static_cast<char*>(data);
  size_t got = 0;
  while (got < len) {
    ssize_t n = ::recv(fd_, p + got, len - got, MSG_DONTWAIT);
    if (n > 0) {
      got += static_cast<size_t>(n);
      continue;
    }
    if (n == 0) throw std::runtime_error("recv: connection closed");
    if (errno != EAGAIN && errno != EWOULDBLOCK && errno != EINTR)
      throw_errno("recv");
    if (!wait_fd(fd_, POLLIN, deadline)) throw_timeout("recv");
  }
}

size_t Socket::peek(void* data, size_t len, TimePoint deadline) {
  while (true) {
    ssize_t n = ::recv(fd_, data, len, MSG_DONTWAIT | MSG_PEEK);
    if (n > 0) return static_cast<size_t>(n);
    if (n == 0) throw std::runtime_error("peek: connection closed");
    if (errno != EAGAIN && errno != EWOULDBLOCK && errno != EINTR)
      throw_errno("peek");
    if (!wait_fd(fd_, POLLIN, deadline)) throw_timeout("peek");
  }
}

Listener::Listener(const std::string& bind) {
  auto [host, port] = split_host_port(bind);
  fd_ = ::socket(AF_INET, SOCK_STREAM, 0);
  if (fd_ < 0) throw_errno("socket");
  int one = 1;
  setsockopt(fd_, SOL_SOCKET, SO_REUSEADDR, &one, sizeof(one));
  struct sockaddr_in addr{};
  addr.sin_family = AF_INET;
  addr.sin_port = htons(static_cast<uint16_t>(port));
  if (host.empty() || host == "0.0.0.0" || host == "::" || host == "[::]") {
    addr.sin_addr.s_addr = INADDR_ANY;
  } else if (inet_pton(AF_INET, host.c_str(), &addr.sin_addr) != 1) {
    // resolve hostname
    struct addrinfo hints{};
    hints.ai_family = AF_INET;
    hints.ai_socktype = SOCK_STREAM;
    struct addrinfo* res = nullptr;
    if (getaddrinfo(host.c_str(), nullptr, &hints, &res) != 0 || !res) {
      ::close(fd_);
      throw std::runtime_error("cannot resolve bind host: " + host);
    }
    addr.sin_addr = reinterpret_cast<sockaddr_in*>(res->ai_addr)->sin_addr;
    freeaddrinfo(res);
  }
  if (::bind(fd_, reinterpret_cast<sockaddr*>(&addr), sizeof(addr)) < 0) {
    int e = errno;
    ::close(fd_);
    errno = e;
    throw_errno("bind " + bind);
  }
  if (::listen(fd_, 128) < 0) {
    int e = errno;
    ::close(fd_);
    errno = e;
    throw_errno("listen");
  }
  socklen_t alen = sizeof(addr);
  getsockname(fd_, reinterpret_cast<sockaddr*>(&addr), &alen);
  port_ = ntohs(addr.sin_port);
  set_nonblocking(fd_, true);
}

Listener::~Listener() { shutdown(); }

void Listener::shutdown() {
  if (fd_ >= 0) {
    ::shutdown(fd_, SHUT_RDWR);
    ::close(fd_);
    fd_ = -1;
  }
}

std::optional<Socket> Listener::accept(Millis timeout) {
  TimePoint deadline = Clock::now() + timeout;
  while (true) {
    if (fd_ < 0) return std::nullopt;
    int cfd = ::accept(fd_, nullptr, nullptr);
    if (cfd >= 0) {
      set_nonblocking(cfd, true);
      int one = 1;
      setsockopt(cfd, IPPROTO_TCP, TCP_NODELAY, &one, sizeof(one));
      return Socket(cfd);
    }
    if (errno != EAGAIN && errno != EWOULDBLOCK && errno != EINTR) {
      if (errno == EBADF || errno == EINVAL) return std::nullopt;  // shut down
      throw_errno("accept");
    }
    int64_t ms = ms_until(deadline);
    if (ms <= 0) return std::nullopt;
    struct pollfd pfd{fd_, POLLIN, 0};
    poll(&pfd, 1, static_cast<int>(std::min<int64_t>(ms, 200)));
  }
}

Socket connect_with_retry(const std::string& host, int port, TimePoint deadline) {
  Millis backoff(10);
  std::string last_err = "unknown";
  while (true) {
    try {
      struct addrinfo hints{};
      hints.ai_family = AF_INET;
      hints.ai_socktype = SOCK_STREAM;
      struct addrinfo* res = nullptr;
      std::string h = host.empty() ? "127.0.0.1" : host;
      if (h == "0.0.0.0") h = "127.0.0.1";
      if (getaddrinfo(h.c_str(), std::to_string(port).c_str(), &hints, &res) != 0 ||
          !res)
        throw std::runtime_error("cannot resolve " + h);
      int fd = ::socket(res->ai_family, SOCK_STREAM, 0);
      if (fd < 0) {
        freeaddrinfo(res);
        throw_errno("socket");
      }
      set_nonblocking(fd, true);
      int rc = ::connect(fd, res->ai_addr, res->ai_addrlen);
      freeaddrinfo(res);
      if (rc < 0 && errno != EINPROGRESS) {
        ::close(fd);
        throw_errno("connect");
      }
      if (rc < 0) {
        if (!wait_fd(fd, POLLOUT, deadline)) {
          ::close(fd);
          throw_timeout("connect");
        }
        int err = 0;
        socklen_t len = sizeof(err);
        getsockopt(fd, SOL_SOCKET, SO_ERROR, &err, &len);
        if (err != 0) {
          ::close(fd);
          errno = err;
          throw_errno("connect");
        }
      }
      int one = 1;
      setsockopt(fd, IPPROTO_TCP, TCP_NODELAY, &one, sizeof(one));
      setsockopt(fd, SOL_SOCKET, SO_KEEPALIVE, &one, sizeof(one));
      return Socket(fd);
    } catch (const std::exception& e) {
      last_err = e.what();
      if (std::string(e.what()).find("timed out") != std::string::npos ||
          ms_until(deadline) <= 0) {
        throw std::runtime_error("connect to " + host + ":" +
                                 std::to_string(port) +
                                 " failed (timed out): " + last_err);
      }
      std::this_thread::sleep_for(
          std::min<Millis>(backoff, Millis(std::max<int64_t>(ms_until(deadline), 1))));
      backoff = std::min<Millis>(backoff * 2, Millis(1000));
    }
  }
}

std::pair<std::string, int> split_host_port(const std::string& addr) {
  std::string a = addr;
  // strip scheme
  auto scheme = a.find("://");
  if (scheme != std::string::npos) a = a.substr(scheme + 3);
  // strip path
  auto slash = a.find('/');
  if (slash != std::string::npos) a = a.substr(0, slash);
  if (!a.empty() && a[0] == '[') {
    auto close = a.find(']');
    if (close == std::string::npos) throw std::runtime_error("bad address: " + addr);
    std::string host = a.substr(1, close - 1);
    int port = 0;
    if (close + 1 < a.size() && a[close + 1] == ':')
      port = std::stoi(a.substr(close + 2));
    return {host, port};
  }
  auto colon = a.rfind(':');
  if (colon == std::string::npos) return {a, 0};
  return {a.substr(0, colon), std::stoi(a.substr(colon + 1))};
}

std::string local_hostname() {
  char buf[256];
  if (gethostname(buf, sizeof(buf)) != 0) return "localhost";
  buf[sizeof(buf) - 1] = '\0';
  return std::string(buf);
}

}  // namespace tft
