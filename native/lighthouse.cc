#include "lighthouse.h"

#include <algorithm>
#include <cstdio>
#include <sstream>

namespace tft {

namespace {
void log_info(const std::string& msg) {
  std::fprintf(stderr, "[lighthouse] %s\n", msg.c_str());
}

// HTML-escape untrusted strings (replica ids / addresses come from clients).
// The reference's askama templates auto-escape; this hand-rolled page must
// do the same to avoid stored XSS on the dashboard.
std::string esc(const std::string& s) {
  std::string out;
  out.reserve(s.size());
  for (char c : s) {
    switch (c) {
      case '&': out += "&amp;"; break;
      case '<': out += "&lt;"; break;
      case '>': out += "&gt;"; break;
      case '"': out += "&quot;"; break;
      case '\'': out += "&#39;"; break;
      default: out.push_back(c);
    }
  }
  return out;
}

// Prometheus label values: escape backslash, double-quote and newline
// (the exposition format's escaping rules for label values).
std::string prom_label(const std::string& s) {
  std::string out;
  out.reserve(s.size());
  for (char c : s) {
    switch (c) {
      case '\\': out += "\\\\"; break;
      case '"': out += "\\\""; break;
      case '\n': out += "\\n"; break;
      default: out.push_back(c);
    }
  }
  return out;
}
}  // namespace

Lighthouse::Lighthouse(const std::string& bind, LighthouseOpts opts,
                       HealthOpts health)
    : opts_(opts),
      ledger_(std::move(health), opts.heartbeat_timeout_ms,
              opts.min_replicas),
      history_(opts.history_path) {
  // Policy event stream: the same events the file sink records, kept in a
  // bounded ring so the in-process policy engine can fold them live.
  if (opts.policy_ring > 0) history_.enable_ring(opts.policy_ring);
  server_ = std::make_unique<RpcServer>(
      bind,
      [this](const std::string& m, const Json& p, TimePoint d) {
        return handle(m, p, d);
      },
      [this](const std::string& m, const std::string& p) {
        return handle_http(m, p);
      });
  tick_thread_ = std::thread([this] { tick_loop(); });
}

Lighthouse::~Lighthouse() { shutdown(); }

std::string Lighthouse::address() const {
  return local_hostname() + ":" + std::to_string(server_->port());
}

void Lighthouse::shutdown() {
  bool was = running_.exchange(false);
  if (!was) return;
  quorum_cv_.notify_all();
  if (tick_thread_.joinable()) tick_thread_.join();
  server_->shutdown();
}

void Lighthouse::tick_loop() {
  while (running_.load()) {
    {
      std::unique_lock<std::mutex> lk(mu_);
      quorum_tick_locked();
    }
    std::this_thread::sleep_for(Millis(opts_.quorum_tick_ms));
  }
}

void Lighthouse::quorum_tick_locked() {
  // Prune long-dead heartbeat entries so replica-id churn (each restart has a
  // fresh uuid-suffixed id) doesn't grow state without bound. Kept for 10x
  // the timeout so the dashboard still shows recently-dead replicas.
  auto now = Clock::now();
  for (auto it = state_.heartbeats.begin(); it != state_.heartbeats.end();) {
    if (now - it->second > Millis(10 * opts_.heartbeat_timeout_ms)) {
      // Drop the history-dedup entry with the heartbeat: replica-id churn
      // would otherwise grow history_telemetry_step_ without bound.
      history_telemetry_step_.erase(it->first);
      it = state_.heartbeats.erase(it);
    } else {
      ++it;
    }
  }
  // Aggregators prune on the same horizon: a dead aggregator's pod has long
  // since failed over to direct mode, and its registry entry must not keep
  // being named as a replacement.
  for (auto it = aggregators_.begin(); it != aggregators_.end();) {
    if (now - it->second.last_tick > Millis(10 * opts_.heartbeat_timeout_ms)) {
      it = aggregators_.erase(it);
    } else {
      ++it;
    }
  }
  // Health ledger tick: probation -> readmission transitions (time-based)
  // and pruning on the same 10x horizon as the heartbeat map above.
  apply_health_events_locked(
      ledger_.tick(now, 10 * opts_.heartbeat_timeout_ms));
  auto [met, reason] = quorum_compute(Clock::now(), state_, opts_);
  if (reason != last_reason_) {
    log_info(reason);
    last_reason_ = reason;
  }
  if (!met) return;
  auto participants = *met;

  std::vector<std::string> commit_failure_ids;
  for (const auto& p : participants)
    if (p.commit_failures > 0) commit_failure_ids.push_back(p.replica_id);

  // Bump quorum_id only when membership changed or a member reported commit
  // failures (so a retried step gets a fresh communicator world).
  if (!state_.prev_quorum.has_value() ||
      quorum_changed(participants, state_.prev_quorum->participants)) {
    state_.quorum_id += 1;
    log_info("Detected quorum change, bumping quorum_id to " +
             std::to_string(state_.quorum_id));
  } else if (!commit_failure_ids.empty()) {
    state_.quorum_id += 1;
    std::string ids;
    for (const auto& id : commit_failure_ids) ids += id + ",";
    log_info("Detected commit failures in [" + ids +
             "], bumping quorum_id to " + std::to_string(state_.quorum_id));
  }

  QuorumSnapshot q;
  q.quorum_id = state_.quorum_id;
  q.participants = participants;
  q.created_ms = epoch_millis_now();
  state_.prev_quorum = q;
  state_.participants.clear();

  latest_quorum_ = q;
  quorum_gen_ += 1;
  quorum_cv_.notify_all();

  if (history_.recording()) {
    int64_t min_step = participants.front().step;
    int64_t max_step = participants.front().step;
    Json rids = Json::array();
    for (const auto& p : participants) {
      rids.push_back(p.replica_id);
      min_step = std::min(min_step, p.step);
      max_step = std::max(max_step, p.step);
    }
    Json e = Json::object();
    e["kind"] = std::string("quorum");
    e["quorum_id"] = q.quorum_id;
    e["participants"] = rids;
    e["min_step"] = min_step;
    e["max_step"] = max_step;
    history_.append(e);
    // A member below the quorum's max step heals into it: record one heal
    // event per lagging member so a replay can reconstruct who recovered
    // from whom-aligned step to which step under which quorum.
    for (const auto& p : participants) {
      if (p.step >= max_step) continue;
      Json h = Json::object();
      h["kind"] = std::string("heal");
      h["replica_id"] = p.replica_id;
      h["from_step"] = p.step;
      h["to_step"] = max_step;
      h["quorum_id"] = q.quorum_id;
      history_.append(h);
    }
  }
}

Json Lighthouse::handle(const std::string& method, const Json& params,
                        TimePoint deadline) {
  if (method == "quorum") return rpc_quorum(params, deadline);
  if (method == "heartbeat") return rpc_heartbeat(params);
  if (method == "agg_tick") return rpc_agg_tick(params);
  if (method == "status") return status_json();
  if (method == "health") return health_json();
  throw RpcError("invalid", "unknown lighthouse method: " + method);
}

Json Lighthouse::rpc_quorum(const Json& params, TimePoint deadline) {
  QuorumMember requester = QuorumMember::from_json(params.get("requester"));
  log_info("Received quorum request for replica " + requester.replica_id);

  std::unique_lock<std::mutex> lk(mu_);
  // Implicit heartbeat + join (the ledger tracks beat continuity too: an
  // ejected replica's probation clock must not reset just because its
  // beats arrive via quorum retries instead of the beat loop).
  state_.heartbeats[requester.replica_id] = Clock::now();
  ledger_.on_heartbeat(requester.replica_id, nullptr, Clock::now());
  state_.participants[requester.replica_id] =
      MemberDetails{Clock::now(), requester};
  uint64_t waiting_gen = quorum_gen_;
  // Proactive tick so a ready quorum resolves without waiting for the timer.
  quorum_tick_locked();

  // Wait for a quorum containing the requester; if one is published without
  // it (possible when this replica joined right after a tick cleared the
  // participant set), re-join and keep waiting (reference re-subscribe loop,
  // src/lighthouse.rs:523-544).
  while (true) {
    bool got = quorum_cv_.wait_until(lk, deadline, [&] {
      return !running_.load() || quorum_gen_ > waiting_gen;
    });
    if (!running_.load()) throw RpcError("unavailable", "lighthouse shutting down");
    if (!got) throw TimeoutError("quorum request timed out");
    waiting_gen = quorum_gen_;
    const QuorumSnapshot& q = *latest_quorum_;
    bool in_quorum = std::any_of(
        q.participants.begin(), q.participants.end(),
        [&](const QuorumMember& m) { return m.replica_id == requester.replica_id; });
    if (in_quorum) {
      Json out = Json::object();
      out["quorum"] = q.to_json();
      return out;
    }
    log_info("Replica " + requester.replica_id + " not in quorum, retrying");
    state_.participants[requester.replica_id] =
        MemberDetails{Clock::now(), requester};
    // refresh the implicit heartbeat like the initial join does: a
    // directly-connected client (no separate beat loop) whose heartbeat
    // expired mid-wait would otherwise be excluded as unhealthy on every
    // retry and spin until its deadline
    state_.heartbeats[requester.replica_id] = Clock::now();
    ledger_.on_heartbeat(requester.replica_id, nullptr, Clock::now());
  }
}

void Lighthouse::apply_beat_locked(const std::string& replica_id,
                                   const Json* telemetry, TimePoint now) {
  state_.heartbeats[replica_id] = now;
  apply_health_events_locked(ledger_.on_heartbeat(replica_id, telemetry, now));
  // History: sample one telemetry snapshot per (replica, step) — beats
  // re-sending the same payload cost nothing, matching the ledger's dedup.
  if (history_.recording() && telemetry != nullptr) {
    int64_t step = telemetry->get_or("step", Json(int64_t{-1})).as_int();
    auto it = history_telemetry_step_.find(replica_id);
    if (it == history_telemetry_step_.end() || it->second != step) {
      history_telemetry_step_[replica_id] = step;
      Json e = Json::object();
      e["kind"] = std::string("telemetry");
      e["replica_id"] = replica_id;
      e["step"] = step;
      e["telemetry"] = *telemetry;
      history_.append(e);
    }
  }
}

std::string Lighthouse::pick_aggregator_locked(TimePoint now) const {
  std::string addr;
  TimePoint best{};
  for (const auto& [aid, info] : aggregators_) {
    if (info.addr.empty()) continue;
    if (now - info.last_tick >= Millis(opts_.heartbeat_timeout_ms)) continue;
    if (addr.empty() || info.last_tick > best) {
      addr = info.addr;
      best = info.last_tick;
    }
  }
  return addr;
}

Json Lighthouse::rpc_heartbeat(const Json& params) {
  std::string replica_id = params.get("replica_id").as_string();
  std::lock_guard<std::mutex> lk(mu_);
  auto now = Clock::now();
  // Optional telemetry payload rides the existing beat; the ledger dedups
  // by step so re-sent payloads cost nothing.
  const Json* telemetry = nullptr;
  Json t;
  if (params.contains("telemetry") && !params.get("telemetry").is_null()) {
    t = params.get("telemetry");
    telemetry = &t;
  }
  apply_beat_locked(replica_id, telemetry, now);
  // The response carries this replica's health summary back to its Manager
  // (surfaced in Manager.timings() and the torchft_health event stream).
  // server_ms lets the beat loop estimate clock skew vs this lighthouse
  // from the RPC round-trip (tracing.py stamps it into span exports).
  Json out = Json::object();
  out["health"] = ledger_.replica_json(replica_id);
  out["server_ms"] = epoch_millis_now();
  // A manager beating directly while configured for an aggregator asks for
  // a replacement; name the freshest live aggregator so the pod re-forms.
  // Flat fleets never send want_aggregator, so their response is unchanged.
  if (params.get_or("want_aggregator", Json(false)).as_bool()) {
    std::string agg = pick_aggregator_locked(now);
    if (!agg.empty()) out["aggregator"] = agg;
  }
  // Optional policy frame piggyback (flat fleets get it directly on the
  // beat reply). Pre-policy managers ignore unknown reply keys, so this is
  // invisible to them; with no frame set, the reply is byte-identical.
  if (policy_frame_.is_object()) out["policy"] = policy_frame_;
  return out;
}

Json Lighthouse::rpc_agg_tick(const Json& params) {
  std::string agg_id = params.get("agg_id").as_string();
  int64_t epoch = params.get("epoch").as_int();
  int64_t seq = params.get("seq").as_int();
  std::lock_guard<std::mutex> lk(mu_);
  auto now = Clock::now();
  AggregatorInfo& info = aggregators_[agg_id];
  // Stale-delta rejection: frames from a previous incarnation (lower epoch)
  // or replayed/reordered frames (non-increasing seq) must not regress the
  // registry — a restarted aggregator's stray in-flight tick could otherwise
  // resurrect a superseded live set.
  if (epoch < info.epoch || (epoch == info.epoch && seq <= info.last_seq))
    throw RpcError("invalid", "stale aggregator delta from " + agg_id +
                                  " (epoch=" + std::to_string(epoch) +
                                  " seq=" + std::to_string(seq) + ")");
  if (epoch > info.epoch) {
    // New incarnation: forget the old live set so beats_same can't lie.
    info.epoch = epoch;
    info.live.clear();
    info.has_live = false;
    log_info("aggregator " + agg_id + " epoch " + std::to_string(epoch));
  }
  info.last_seq = seq;
  info.addr = params.get_or("addr", Json(std::string())).as_string();
  info.last_tick = now;
  info.ticks += 1;

  if (params.get_or("beats_same", Json(false)).as_bool()) {
    // Reuse the stored live set. If we've never seen one this incarnation
    // (e.g. this lighthouse restarted), fail the tick: the aggregator treats
    // any error as a failed tick and re-sends the full set next frame.
    if (!info.has_live)
      throw RpcError("invalid",
                     "beats_same from " + agg_id + " with no known live set");
  } else if (params.contains("beats")) {
    std::set<std::string> live;
    for (const auto& b : params.get("beats").as_array())
      live.insert(b.as_string());
    info.live = std::move(live);
    info.has_live = true;
  }
  // The aggregator vouches for pod freshness: every live replica beats.
  for (const auto& rid : info.live) apply_beat_locked(rid, nullptr, now);
  // Telemetry deltas (only replicas whose step advanced since last ack).
  if (params.contains("telemetry")) {
    for (const auto& [rid, t] : params.get("telemetry").as_object())
      apply_beat_locked(rid, &t, now);
  }
  // Quorum joiners ride the tick. Re-registering an already-joined replica
  // must preserve its original join time — the join_timeout straggler wait
  // is measured from first join, and the aggregator re-sends pending
  // joiners every tick.
  //
  // Generation gate: a frame built before this aggregator saw the latest
  // quorum (quorum_gen_seen behind ours) may still carry joiners that the
  // in-flight quorum already satisfied — registering them would pollute the
  // next round's participant set with replicas that are no longer waiting
  // (and can trip a premature fast quorum). Skip them; the response below
  // syncs the aggregator's generation, it drops satisfied joiners, and any
  // genuinely-still-pending joiner is re-sent next tick (one tick of added
  // join latency only in the publish race window).
  int64_t gen_seen = params.get_or("quorum_gen_seen", Json(int64_t{0})).as_int();
  bool joiners_current = gen_seen >= static_cast<int64_t>(quorum_gen_);
  bool had_joiners = false;
  if (joiners_current && params.contains("joiners")) {
    for (const auto& jm : params.get("joiners").as_array()) {
      QuorumMember m = QuorumMember::from_json(jm);
      auto it = state_.participants.find(m.replica_id);
      if (it != state_.participants.end()) {
        it->second.member = m;
      } else {
        state_.participants[m.replica_id] = MemberDetails{now, m};
      }
      apply_beat_locked(m.replica_id, nullptr, now);
      had_joiners = true;
    }
  }
  // Proactive tick (mirrors rpc_quorum) so a ready quorum resolves within
  // one aggregator tick instead of waiting for the timer.
  if (had_joiners) quorum_tick_locked();

  Json out = Json::object();
  out["server_ms"] = epoch_millis_now();
  out["quorum_gen"] = static_cast<int64_t>(quorum_gen_);
  if (latest_quorum_ && static_cast<int64_t>(quorum_gen_) > gen_seen)
    out["quorum"] = latest_quorum_->to_json();
  // Health fan-back is bounded: only replicas with telemetry in THIS frame
  // get a summary (their managers see it on the next pod beat).
  if (params.contains("telemetry")) {
    Json h = Json::object();
    for (const auto& [rid, t] : params.get("telemetry").as_object()) {
      (void)t;
      h[rid] = ledger_.replica_json(rid);
    }
    out["health"] = h;
  }
  // Policy frame piggyback: the aggregator caches the newest frame and
  // fans it out to its pod on heartbeat replies. Riding the existing tick
  // means zero new RPC methods; pre-policy aggregators ignore the key.
  if (policy_frame_.is_object()) out["policy"] = policy_frame_;
  return out;
}

void Lighthouse::set_policy(const Json& frame) {
  std::lock_guard<std::mutex> lk(mu_);
  // An empty object (or non-object) clears the frame — the kill switch:
  // replies go back to their pre-policy shape on the next beat/tick.
  if (frame.is_object() && !frame.as_object().empty())
    policy_frame_ = frame;
  else
    policy_frame_ = Json();
}

std::string Lighthouse::policy_json() {
  std::lock_guard<std::mutex> lk(mu_);
  return policy_frame_.is_object() ? policy_frame_.dump() : "{}";
}

std::string Lighthouse::drain_events() {
  // The ring is internally locked; skipping mu_ keeps the engine's poll
  // off the quorum/beat critical path.
  Json out = Json::array();
  for (auto& e : history_.drain_ring()) out.push_back(std::move(e));
  return out.dump();
}

std::string Lighthouse::retune_health(const Json& partial) {
  std::lock_guard<std::mutex> lk(mu_);
  Json merged = ledger_.opts().to_json();
  if (partial.is_object()) {
    for (const auto& [k, v] : partial.as_object()) merged[k] = v;
  }
  HealthOpts next = HealthOpts::from_json(merged);
  ledger_.set_opts(next);
  Json e = Json::object();
  e["kind"] = std::string("health_retune");
  e["opts"] = next.to_json();
  history_.append(e);
  return next.to_json().dump();
}

void Lighthouse::apply_health_events_locked(const std::vector<Json>& events) {
  for (const auto& e : events) {
    log_info("health: " + e.dump());
    // Ledger events already carry "kind" (straggler_warn/eject/readmit);
    // they append to history as-is.
    history_.append(e);
  }
  state_.excluded = ledger_.exclusions();
}

Json Lighthouse::health_json() {
  std::lock_guard<std::mutex> lk(mu_);
  Json j = ledger_.to_json(Clock::now());
  j["quorum_id"] = state_.quorum_id;
  j["min_replicas"] = opts_.min_replicas;
  return j;
}

Json Lighthouse::status_json() {
  std::lock_guard<std::mutex> lk(mu_);
  Json j = Json::object();
  j["quorum_id"] = state_.quorum_id;
  j["prev_quorum"] =
      state_.prev_quorum ? state_.prev_quorum->to_json() : Json();
  Json joined = Json::array();
  for (const auto& [rid, d] : state_.participants) joined.push_back(rid);
  j["participants"] = joined;
  Json beats = Json::object();
  auto now = Clock::now();
  for (const auto& [rid, last] : state_.heartbeats) {
    beats[rid] = static_cast<int64_t>(
        std::chrono::duration_cast<Millis>(now - last).count());
  }
  j["heartbeat_ages_ms"] = beats;
  Json ex = Json::array();
  for (const auto& rid : state_.excluded) ex.push_back(rid);
  j["excluded"] = ex;
  Json aggs = Json::object();
  for (const auto& [aid, info] : aggregators_) {
    Json a = Json::object();
    a["addr"] = info.addr;
    a["epoch"] = info.epoch;
    a["seq"] = info.last_seq;
    a["age_ms"] = static_cast<int64_t>(
        std::chrono::duration_cast<Millis>(now - info.last_tick).count());
    a["live"] = static_cast<int64_t>(info.live.size());
    a["ticks"] = static_cast<int64_t>(info.ticks);
    aggs[aid] = a;
  }
  j["aggregators"] = aggs;
  // Per-method receive accounting — the fleet bench reads this to compare
  // heartbeat fan-in bytes between flat and 2-level topologies.
  j["rx"] = server_->rx_stats();
  return j;
}

std::string Lighthouse::metrics_text() {
  std::lock_guard<std::mutex> lk(mu_);
  auto now = Clock::now();
  std::ostringstream os;
  auto gauge = [&os](const char* name, const char* help, double v) {
    os << "# HELP " << name << " " << help << "\n# TYPE " << name
       << " gauge\n" << name << " " << v << "\n";
  };

  gauge("torchft_lighthouse_quorum_id", "Current quorum id",
        static_cast<double>(state_.quorum_id));
  gauge("torchft_lighthouse_fleet_size",
        "Participants in the most recent quorum",
        state_.prev_quorum
            ? static_cast<double>(state_.prev_quorum->participants.size())
            : 0.0);
  gauge("torchft_lighthouse_joining",
        "Replicas currently waiting to join the next quorum",
        static_cast<double>(state_.participants.size()));
  gauge("torchft_lighthouse_excluded",
        "Replicas proactively excluded by the health ledger",
        static_cast<double>(state_.excluded.size()));
  os << "# HELP torchft_lighthouse_history_events_total Recorded-history"
        " events written\n"
     << "# TYPE torchft_lighthouse_history_events_total counter\n"
     << "torchft_lighthouse_history_events_total "
     << history_.events_written() << "\n";

  gauge("torchft_lighthouse_policy_seq",
        "Version of the policy frame riding beat/tick replies (0 = none)",
        policy_frame_.is_object()
            ? static_cast<double>(
                  policy_frame_.get_or("policy_seq", Json(int64_t{0})).as_int())
            : 0.0);
  gauge("torchft_lighthouse_aggregators",
        "Live lighthouse aggregators in the registry",
        static_cast<double>(aggregators_.size()));
  {
    Json rx = server_->rx_stats();
    os << "# HELP torchft_lighthouse_rx_bytes_total Request frame bytes"
          " received, by RPC method\n"
       << "# TYPE torchft_lighthouse_rx_bytes_total counter\n";
    for (const auto& [method, s] : rx.as_object()) {
      os << "torchft_lighthouse_rx_bytes_total{method=\""
         << prom_label(method) << "\"} " << s.get("bytes").as_int() << "\n";
    }
  }

  // Per-replica families are capped at metrics_per_replica_limit series
  // (lexicographic, so the emitted set is stable across scrapes); the tail
  // collapses into aggregate min/median/max so fleet-scale cardinality
  // stays bounded. <= limit replicas emits exactly the pre-cap format.
  const size_t limit = static_cast<size_t>(
      std::max<int64_t>(opts_.metrics_per_replica_limit, 0));
  auto emit_family = [&os, limit](const char* name, const char* help,
                                  const char* type,
                                  const std::vector<std::pair<std::string, double>>&
                                      vals) {
    os << "# HELP " << name << " " << help << "\n# TYPE " << name << " "
       << type << "\n";
    std::vector<double> tail;
    size_t emitted = 0;
    for (const auto& [rid, v] : vals) {
      if (emitted < limit) {
        os << name << "{replica=\"" << prom_label(rid) << "\"} " << v << "\n";
        emitted += 1;
      } else {
        tail.push_back(v);
      }
    }
    if (!tail.empty()) {
      std::sort(tail.begin(), tail.end());
      double med = tail.size() % 2 == 1
                       ? tail[tail.size() / 2]
                       : (tail[tail.size() / 2 - 1] + tail[tail.size() / 2]) / 2.0;
      os << name << "{replica=\"_tail\",stat=\"min\"} " << tail.front() << "\n"
         << name << "{replica=\"_tail\",stat=\"median\"} " << med << "\n"
         << name << "{replica=\"_tail\",stat=\"max\"} " << tail.back() << "\n";
    }
  };

  std::vector<std::pair<std::string, double>> ages;
  ages.reserve(state_.heartbeats.size());
  for (const auto& [rid, last] : state_.heartbeats) {
    ages.emplace_back(
        rid, static_cast<double>(
                 std::chrono::duration_cast<Millis>(now - last).count()));
  }
  emit_family("torchft_lighthouse_heartbeat_age_ms",
              "Milliseconds since the replica's last heartbeat", "gauge",
              ages);
  gauge("torchft_lighthouse_heartbeat_replicas",
        "Replicas currently tracked in the heartbeat map",
        static_cast<double>(state_.heartbeats.size()));
  gauge("torchft_lighthouse_metrics_replica_limit",
        "Per-replica series cap (TORCHFT_METRICS_PER_REPLICA_LIMIT)",
        static_cast<double>(limit));

  // Per-replica health ledger view. state codes match HealthState:
  // 0=ok 1=warn 2=ejected 3=probation.
  Json h = ledger_.to_json(now);
  const auto& reps = h.get("replicas").as_object();
  std::vector<std::pair<std::string, double>> states, scores, ejections,
      readmissions;
  for (const auto& [rid, r] : reps) {
    std::string state = r.get("state").as_string();
    int code = state == "warn" ? 1 : state == "ejected" ? 2
               : state == "probation" ? 3 : 0;
    states.emplace_back(rid, static_cast<double>(code));
    scores.emplace_back(rid, r.get("score").as_double());
    ejections.emplace_back(rid,
                           static_cast<double>(r.get("ejections").as_int()));
    readmissions.emplace_back(
        rid, static_cast<double>(r.get("readmissions").as_int()));
  }
  emit_family("torchft_lighthouse_replica_state",
              "Health state code (0=ok 1=warn 2=ejected 3=probation)",
              "gauge", states);
  emit_family("torchft_lighthouse_straggler_score",
              "Modified-z straggler score (quorum-relative compute time)",
              "gauge", scores);
  emit_family("torchft_lighthouse_replica_ejections_total",
              "Times the replica was ejected by the health policy", "counter",
              ejections);
  emit_family("torchft_lighthouse_replica_readmissions_total",
              "Times the replica was readmitted after probation", "counter",
              readmissions);
  return os.str();
}

std::string Lighthouse::status_html() {
  Json s = status_json();
  std::ostringstream os;
  os << "<!doctype html><html><head><title>torchft_tpu lighthouse</title>"
     << "<style>body{font-family:monospace;margin:2em}table{border-collapse:"
        "collapse}td,th{border:1px solid #888;padding:4px 8px}</style></head>"
     << "<body><h1>torchft_tpu lighthouse</h1>"
     << "<p>quorum_id: " << s.get("quorum_id").as_int() << "</p>";
  os << "<h2>heartbeats</h2><table><tr><th>replica</th><th>age (ms)</th>"
        "<th></th></tr>";
  for (const auto& [rid, age] : s.get("heartbeat_ages_ms").as_object()) {
    os << "<tr><td>" << esc(rid) << "</td><td>" << age.as_int() << "</td><td>"
       << "<form method=post action=\"/replica/" << esc(rid)
       << "/kill\"><button>kill</button></form></td></tr>";
  }
  os << "</table>";
  if (!s.get("prev_quorum").is_null()) {
    os << "<h2>previous quorum</h2><table><tr><th>replica</th><th>step</th>"
          "<th>address</th></tr>";
    for (const auto& p : s.get("prev_quorum").get("participants").as_array()) {
      os << "<tr><td>" << esc(p.get("replica_id").as_string()) << "</td><td>"
         << p.get("step").as_int() << "</td><td>"
         << esc(p.get("address").as_string()) << "</td></tr>";
    }
    os << "</table>";
  }
  os << "</body></html>";
  return os.str();
}

std::tuple<std::string, std::string, std::string> Lighthouse::handle_http(
    const std::string& method, const std::string& path) {
  try {
    if (path == "/" || path == "/index.html")
      return {"200 OK", "text/html", status_html()};
    if (path == "/status") return {"200 OK", "application/json", status_json().dump()};
    if (path == "/health") return {"200 OK", "application/json", health_json().dump()};
    if (path == "/metrics")
      return {"200 OK", "text/plain; version=0.0.4", metrics_text()};
    // POST /replica/{id}/kill — forward a Kill RPC to that replica's manager.
    const std::string prefix = "/replica/";
    if (path.rfind(prefix, 0) == 0 && path.size() > prefix.size()) {
      // destructive endpoint: POST only — a GET (browser prefetch, crawler
      // walking the dashboard links) must never kill a replica
      if (method != "POST")
        return {"405 Method Not Allowed", "text/plain", "kill requires POST\n"};
      auto rest = path.substr(prefix.size());
      auto slash = rest.find('/');
      if (slash != std::string::npos && rest.substr(slash) == "/kill") {
        std::string replica_id = rest.substr(0, slash);
        std::string addr;
        {
          std::lock_guard<std::mutex> lk(mu_);
          if (state_.prev_quorum) {
            for (const auto& p : state_.prev_quorum->participants)
              if (p.replica_id == replica_id) addr = p.address;
          }
          auto it = state_.participants.find(replica_id);
          if (addr.empty() && it != state_.participants.end())
            addr = it->second.member.address;
        }
        if (addr.empty())
          return {"404 Not Found", "text/plain", "unknown replica " + replica_id};
        try {
          RpcClient client(addr, Millis(5000));
          Json params = Json::object();
          params["msg"] = std::string("killed from lighthouse dashboard");
          client.call("kill", params, Millis(5000));
        } catch (const std::exception&) {
          // The manager exits on kill; connection errors are expected.
        }
        return {"200 OK", "text/plain", "killed " + replica_id};
      }
    }
    return {"404 Not Found", "text/plain", "not found"};
  } catch (const std::exception& e) {
    return {"500 Internal Server Error", "text/plain", e.what()};
  }
}

}  // namespace tft
