// C++ unit tests for the native control plane, mirroring the reference's
// inline Rust tests: quorum_compute edge cases (src/lighthouse.rs:627-1071),
// compute_quorum_results recovery math (src/manager.rs:881-1108), 2-phase
// should_commit (src/manager.rs:656-702), and gRPC-style e2e with in-process
// servers (src/manager.rs:976-1020).

#include <cassert>
#include <cstdio>
#include <thread>
#include <vector>

#include "healthwatch.h"
#include "kvstore.h"
#include "lighthouse.h"
#include "manager_server.h"
#include "quorum.h"
#include "wire.h"

using namespace tft;

static int failures = 0;
#define CHECK(cond)                                                        \
  do {                                                                     \
    if (!(cond)) {                                                         \
      std::fprintf(stderr, "FAIL %s:%d: %s\n", __FILE__, __LINE__, #cond); \
      ++failures;                                                          \
    }                                                                      \
  } while (0)

static QuorumMember member(const std::string& id, int64_t step = 0) {
  QuorumMember m;
  m.replica_id = id;
  m.address = "addr_" + id;
  m.store_address = "store_" + id;
  m.step = step;
  m.world_size = 1;
  return m;
}

// ---------------------------------------------------------- quorum_compute
static void test_quorum_fast_path() {
  LighthouseOpts opts;
  opts.min_replicas = 1;
  opts.join_timeout_ms = 60000;
  opts.heartbeat_timeout_ms = 5000;
  TimePoint now = Clock::now();

  LighthouseState state;
  state.participants["a"] = {now, member("a")};
  state.heartbeats["a"] = now;
  QuorumSnapshot prev;
  prev.quorum_id = 1;
  prev.participants = {member("a")};
  state.prev_quorum = prev;

  auto [met, reason] = quorum_compute(now, state, opts);
  CHECK(met.has_value());
  CHECK(reason.find("Fast quorum") != std::string::npos);
}

static void test_quorum_join_timeout_straggler() {
  LighthouseOpts opts;
  opts.min_replicas = 1;
  opts.join_timeout_ms = 60000;
  opts.heartbeat_timeout_ms = 5000;
  TimePoint now = Clock::now();

  // "c" is heartbeating (alive) but has not joined the quorum -> wait for it
  // (majority 2/3 is satisfied, so the straggler gate is what blocks).
  LighthouseState state;
  state.participants["a"] = {now, member("a")};
  state.participants["b"] = {now, member("b")};
  state.heartbeats["a"] = now;
  state.heartbeats["b"] = now;
  state.heartbeats["c"] = now;

  auto [met, reason] = quorum_compute(now, state, opts);
  CHECK(!met.has_value());
  CHECK(reason.find("straggler") != std::string::npos);

  // After the join timeout expires the quorum shrinks to the joined members.
  state.participants["a"].joined = now - Millis(70000);
  auto [met2, reason2] = quorum_compute(now, state, opts);
  CHECK(met2.has_value());
  CHECK(met2->size() == 2);
}

static void test_quorum_min_replicas() {
  LighthouseOpts opts;
  opts.min_replicas = 2;
  opts.join_timeout_ms = 0;
  opts.heartbeat_timeout_ms = 5000;
  TimePoint now = Clock::now();

  LighthouseState state;
  state.participants["a"] = {now, member("a")};
  state.heartbeats["a"] = now;
  auto [met, reason] = quorum_compute(now, state, opts);
  CHECK(!met.has_value());
  CHECK(reason.find("min_replicas") != std::string::npos);

  state.participants["b"] = {now, member("b")};
  state.heartbeats["b"] = now;
  auto [met2, _] = quorum_compute(now, state, opts);
  CHECK(met2.has_value());
  CHECK(met2->size() == 2);
}

static void test_quorum_expired_heartbeat() {
  LighthouseOpts opts;
  opts.min_replicas = 1;
  opts.join_timeout_ms = 0;
  opts.heartbeat_timeout_ms = 5000;
  TimePoint now = Clock::now();

  LighthouseState state;
  state.participants["a"] = {now, member("a")};
  state.participants["b"] = {now, member("b")};
  state.heartbeats["a"] = now;
  state.heartbeats["b"] = now - Millis(10000);  // expired

  auto [met, _] = quorum_compute(now, state, opts);
  CHECK(met.has_value());
  CHECK(met->size() == 1);
  CHECK((*met)[0].replica_id == "a");
}

static void test_quorum_split_brain() {
  LighthouseOpts opts;
  opts.min_replicas = 1;
  opts.join_timeout_ms = 0;
  opts.heartbeat_timeout_ms = 5000;
  TimePoint now = Clock::now();

  // 1 joined, 2 alive -> 1 <= 2/2 -> no quorum (split-brain guard).
  LighthouseState state;
  state.participants["a"] = {now - Millis(1000), member("a")};
  state.heartbeats["a"] = now;
  state.heartbeats["b"] = now;
  auto [met, reason] = quorum_compute(now, state, opts);
  CHECK(!met.has_value());
  CHECK(reason.find("at least half") != std::string::npos);

  // 2 joined of 3 alive -> majority -> quorum (join_timeout=0).
  state.participants["b"] = {now, member("b")};
  state.heartbeats["c"] = now;
  auto [met2, _] = quorum_compute(now, state, opts);
  CHECK(met2.has_value());
}

static void test_quorum_shrink_only() {
  LighthouseOpts opts;
  opts.min_replicas = 1;
  opts.join_timeout_ms = 0;
  opts.heartbeat_timeout_ms = 5000;
  TimePoint now = Clock::now();

  LighthouseState state;
  QuorumSnapshot prev;
  prev.quorum_id = 1;
  prev.participants = {member("a"), member("b")};
  state.prev_quorum = prev;

  auto m_a = member("a");
  m_a.shrink_only = true;
  state.participants["a"] = {now, {m_a}};
  state.participants["c"] = {now, member("c")};  // new joiner, filtered out
  state.heartbeats["a"] = now;
  state.heartbeats["c"] = now;

  auto [met, _] = quorum_compute(now, state, opts);
  CHECK(met.has_value());
  CHECK(met->size() == 1);
  CHECK((*met)[0].replica_id == "a");
}

// -------------------------------------------------- compute_quorum_results
static QuorumSnapshot make_quorum(std::vector<QuorumMember> ms) {
  QuorumSnapshot q;
  q.quorum_id = 7;
  q.participants = std::move(ms);
  return q;
}

static void test_results_first_step_force_recover() {
  // All replicas at step 0 with init_sync: everyone except the primary heals.
  auto q = make_quorum({member("a", 0), member("b", 0), member("c", 0)});
  auto ra = compute_quorum_results("a", 0, q, true);
  CHECK(!ra.heal);  // "a" is primary (group_rank 0 % 3 max participants... )
  CHECK(ra.recover_dst_replica_ranks.size() == 2);
  auto rb = compute_quorum_results("b", 0, q, true);
  CHECK(rb.heal);
  CHECK(rb.recover_src_replica_rank.has_value() &&
        *rb.recover_src_replica_rank == 0);
  CHECK(rb.recover_src_manager_address == "addr_a");
  // Without init_sync nobody heals at step 0.
  auto rb2 = compute_quorum_results("b", 0, q, false);
  CHECK(!rb2.heal);
}

static void test_results_behind_replica_heals() {
  auto q = make_quorum({member("a", 10), member("b", 7), member("c", 10)});
  auto rb = compute_quorum_results("b", 0, q, true);
  CHECK(rb.heal);
  CHECK(rb.max_step == 10);
  CHECK(rb.replica_rank == 1);
  CHECK(rb.replica_world_size == 3);
  CHECK(rb.max_world_size == 2);
  CHECK(!rb.max_replica_rank.has_value());
  // Source must be one of the up-to-date replicas (ranks 0 or 2).
  CHECK(rb.recover_src_replica_rank.has_value());
  CHECK(*rb.recover_src_replica_rank == 0 || *rb.recover_src_replica_rank == 2);

  auto ra = compute_quorum_results("a", 0, q, true);
  CHECK(!ra.heal);
  CHECK(ra.max_replica_rank.has_value() && *ra.max_replica_rank == 0);
  // a's dst list + c's dst list together must cover replica 1.
  auto rc = compute_quorum_results("c", 0, q, true);
  size_t total = ra.recover_dst_replica_ranks.size() +
                 rc.recover_dst_replica_ranks.size();
  CHECK(total == 1);
}

static void test_results_store_spread_across_group_ranks() {
  auto q = make_quorum({member("a", 5), member("b", 5)});
  auto r0 = compute_quorum_results("a", 0, q, true);
  auto r1 = compute_quorum_results("a", 1, q, true);
  CHECK(r0.store_address == "store_a");
  CHECK(r1.store_address == "store_b");
}

static void test_results_not_in_quorum() {
  auto q = make_quorum({member("a", 0)});
  bool threw = false;
  try {
    compute_quorum_results("z", 0, q, true);
  } catch (const RpcError& e) {
    threw = e.code == "not_found";
  }
  CHECK(threw);
}

static void test_results_commit_failures_max() {
  auto a = member("a", 3);
  a.commit_failures = 2;
  auto q = make_quorum({a, member("b", 3)});
  auto r = compute_quorum_results("b", 0, q, true);
  CHECK(r.commit_failures == 2);
  CHECK(r.replica_ids.size() == 2);
}

// ----------------------------------------------------------------- wire e2e
static void test_wire_echo_and_timeout() {
  RpcServer server("127.0.0.1:0", [](const std::string& method, const Json& p,
                                     TimePoint deadline) -> Json {
    if (method == "echo") return p;
    if (method == "sleep") {
      std::this_thread::sleep_for(Millis(p.get("ms").as_int()));
      return Json::object();
    }
    if (method == "block_until_deadline") {
      while (Clock::now() < deadline) std::this_thread::sleep_for(Millis(5));
      throw TimeoutError("deadline reached");
    }
    throw RpcError("invalid", "unknown");
  });

  RpcClient client("127.0.0.1:" + std::to_string(server.port()), Millis(2000));
  Json p = Json::object();
  p["x"] = int64_t{42};
  Json r = client.call("echo", p, Millis(2000));
  CHECK(r.get("x").as_int() == 42);

  bool timed_out = false;
  try {
    client.call("block_until_deadline", Json::object(), Millis(200));
  } catch (const TimeoutError&) {
    timed_out = true;
  }
  CHECK(timed_out);

  bool invalid = false;
  try {
    client.call("nope", Json::object(), Millis(2000));
  } catch (const RpcError& e) {
    invalid = e.code == "invalid";
  }
  CHECK(invalid);
  server.shutdown();
}

// ------------------------------------------------------------- kvstore e2e
static void test_kvstore() {
  KvStoreServer store("127.0.0.1:0");
  RpcClient client("127.0.0.1:" + std::to_string(store.port()), Millis(2000));

  Json setp = Json::object();
  setp["key"] = std::string("k1");
  setp["value"] = std::string("v1");
  client.call("set", setp, Millis(2000));

  Json getp = Json::object();
  getp["key"] = std::string("k1");
  CHECK(client.call("get", getp, Millis(2000)).get("value").as_string() == "v1");

  // Blocking get resolved by a concurrent set.
  std::thread setter([&] {
    std::this_thread::sleep_for(Millis(100));
    Json p = Json::object();
    p["key"] = std::string("k2");
    p["value"] = std::string("v2");
    RpcClient c2("127.0.0.1:" + std::to_string(store.port()), Millis(2000));
    c2.call("set", p, Millis(2000));
  });
  Json get2 = Json::object();
  get2["key"] = std::string("k2");
  CHECK(client.call("get", get2, Millis(5000)).get("value").as_string() == "v2");
  setter.join();

  // Atomic add (barrier counter pattern).
  Json addp = Json::object();
  addp["key"] = std::string("ctr");
  addp["amount"] = int64_t{1};
  CHECK(client.call("add", addp, Millis(2000)).get("value").as_int() == 1);
  CHECK(client.call("add", addp, Millis(2000)).get("value").as_int() == 2);

  // Timeout on missing key.
  bool timed_out = false;
  try {
    Json p = Json::object();
    p["key"] = std::string("missing");
    client.call("get", p, Millis(200));
  } catch (const TimeoutError&) {
    timed_out = true;
  }
  CHECK(timed_out);
  store.shutdown();
}

// --------------------------------------------------- lighthouse+manager e2e
static void test_lighthouse_manager_e2e() {
  LighthouseOpts lopts;
  lopts.min_replicas = 2;
  lopts.join_timeout_ms = 100;
  lopts.quorum_tick_ms = 20;
  lopts.heartbeat_timeout_ms = 5000;
  Lighthouse lighthouse("127.0.0.1:0", lopts);
  std::string lh_addr = "127.0.0.1:" + std::to_string(lighthouse.port());

  ManagerOpts mo_a;
  mo_a.replica_id = "rep_a";
  mo_a.lighthouse_addr = lh_addr;
  mo_a.hostname = "127.0.0.1";
  mo_a.bind = "127.0.0.1:0";
  mo_a.store_addr = "store_a";
  mo_a.world_size = 2;  // two ranks in this group
  ManagerServer mgr_a(mo_a);

  ManagerOpts mo_b = mo_a;
  mo_b.replica_id = "rep_b";
  mo_b.store_addr = "store_b";
  mo_b.world_size = 1;
  ManagerServer mgr_b(mo_b);

  auto quorum_call = [](int port, int64_t group_rank, int64_t step) {
    RpcClient c("127.0.0.1:" + std::to_string(port), Millis(2000));
    Json p = Json::object();
    p["group_rank"] = group_rank;
    p["step"] = step;
    p["checkpoint_metadata"] = std::string("meta");
    p["init_sync"] = true;
    return c.call("quorum", p, Millis(10000));
  };

  // Group a needs both ranks to arrive before it forwards to the lighthouse.
  Json ra0, ra1, rb0;
  std::thread ta0([&] { ra0 = quorum_call(mgr_a.port(), 0, 0); });
  std::thread ta1([&] { ra1 = quorum_call(mgr_a.port(), 1, 0); });
  std::thread tb0([&] { rb0 = quorum_call(mgr_b.port(), 0, 0); });
  ta0.join();
  ta1.join();
  tb0.join();

  CHECK(ra0.get("replica_world_size").as_int() == 2);
  CHECK(ra0.get("quorum_id").as_int() == rb0.get("quorum_id").as_int());
  CHECK(ra0.get("replica_rank").as_int() == 0);   // rep_a sorts first
  CHECK(rb0.get("replica_rank").as_int() == 1);
  // Rank 1 of group a uses the second max-participant's store.
  CHECK(ra0.get("store_address").as_string() == "store_a");
  CHECK(ra1.get("store_address").as_string() == "store_b");
  // init_sync at step 0: non-primary heals from primary.
  CHECK(rb0.get("heal").as_bool() == true);
  CHECK(ra0.get("heal").as_bool() == false);

  // checkpoint_metadata fetch.
  RpcClient ca("127.0.0.1:" + std::to_string(mgr_a.port()), Millis(2000));
  Json cp = Json::object();
  cp["rank"] = int64_t{0};
  CHECK(ca.call("checkpoint_metadata", cp, Millis(2000))
            .get("checkpoint_metadata")
            .as_string() == "meta");

  // 2-phase should_commit: one rank voting false vetoes the group.
  auto vote = [](int port, int64_t rank, bool ok) {
    RpcClient c("127.0.0.1:" + std::to_string(port), Millis(2000));
    Json p = Json::object();
    p["group_rank"] = rank;
    p["step"] = int64_t{0};
    p["should_commit"] = ok;
    return c.call("should_commit", p, Millis(5000)).get("should_commit").as_bool();
  };
  bool d0 = false, d1 = false;
  std::thread v0([&] { d0 = vote(mgr_a.port(), 0, true); });
  std::thread v1([&] { d1 = vote(mgr_a.port(), 1, false); });
  v0.join();
  v1.join();
  CHECK(d0 == false && d1 == false);

  std::thread v2([&] { d0 = vote(mgr_a.port(), 0, true); });
  std::thread v3([&] { d1 = vote(mgr_a.port(), 1, true); });
  v2.join();
  v3.join();
  CHECK(d0 == true && d1 == true);

  // Step isolation: votes are keyed by step, so a lone vote for a NEW step
  // must not be completed by residue from the decided step-0 rounds — it
  // times out instead of returning a stale decision (regression).
  {
    RpcClient c("127.0.0.1:" + std::to_string(mgr_a.port()), Millis(2000));
    Json p = Json::object();
    p["group_rank"] = int64_t{0};
    p["step"] = int64_t{1};
    p["should_commit"] = true;
    bool threw = false;
    try {
      c.call("should_commit", p, Millis(300));
    } catch (const std::exception&) {
      threw = true;
    }
    CHECK(threw);
    // the full round for step 1 then completes normally (rank 0 re-votes)
    bool e0 = false, e1 = false;
    std::thread w0([&] { e0 = vote(mgr_a.port(), 0, true); });
    std::thread w1([&] { e1 = vote(mgr_a.port(), 1, true); });
    // NB: vote() uses step 0 — a fresh retry round for step 0; the point
    // above established step-1 votes never bleed into it
    w0.join();
    w1.join();
    CHECK(e0 == true && e1 == true);
  }

  // Second quorum round: fast path (same membership) keeps quorum_id stable.
  Json ra0b, ra1b, rb0b;
  std::thread sa0([&] { ra0b = quorum_call(mgr_a.port(), 0, 1); });
  std::thread sa1([&] { ra1b = quorum_call(mgr_a.port(), 1, 1); });
  std::thread sb0([&] { rb0b = quorum_call(mgr_b.port(), 0, 1); });
  sa0.join();
  sa1.join();
  sb0.join();
  CHECK(ra0b.get("quorum_id").as_int() == ra0.get("quorum_id").as_int());

  mgr_a.shutdown();
  mgr_b.shutdown();
  lighthouse.shutdown();
}

// -------------------------------------------------------------- healthwatch
static void test_health_scores_straggler() {
  HealthOpts opts;
  opts.min_samples = 3;
  std::map<std::string, std::vector<double>> windows;
  windows["a"] = {1.0, 1.0, 1.0, 1.0};
  windows["b"] = {1.0, 1.1, 0.9, 1.0};
  windows["c"] = {10.0, 10.0, 10.0, 10.0};
  windows["warming"] = {10.0};  // below min_samples: unscored, no influence
  auto scores = straggler_scores(windows, opts);
  CHECK(scores["c"] > opts.eject_z);
  CHECK(scores["a"] < opts.warn_z);
  CHECK(scores["b"] < opts.warn_z);
  CHECK(scores["warming"] == 0.0);
  // 1-replica peer group: nothing to compare against
  std::map<std::string, std::vector<double>> solo;
  solo["a"] = {10.0, 10.0, 10.0};
  auto s1 = straggler_scores(solo, opts);
  CHECK(s1["a"] == 0.0);
}

static void test_health_ledger_eject_and_readmit() {
  HealthOpts opts;
  opts.mode = "eject";
  opts.min_samples = 3;
  opts.eject_steps = 2;
  opts.probation_ms = 1000;
  opts.probe_ok = 2;
  HealthLedger ledger(opts, /*heartbeat_timeout_ms=*/5000, /*min_replicas=*/1);
  TimePoint base = Clock::now();
  auto beat = [&](const std::string& rid, int64_t step, double step_s,
                  int64_t t_ms) {
    Json t = Json::object();
    t["step"] = step;
    t["step_s"] = step_s;
    t["wire_s"] = 0.0;
    return ledger.on_heartbeat(rid, &t, base + Millis(t_ms));
  };
  bool ejected = false, warned = false;
  for (int64_t step = 1; step <= 8 && !ejected; ++step) {
    beat("a", step, 1.0, step * 10);
    beat("b", step, 1.0, step * 10);
    for (const auto& e : beat("c", step, 10.0, step * 10)) {
      std::string kind = e.get("kind").as_string();
      if (kind == "straggler_warn") warned = true;
      if (kind == "eject") ejected = true;
    }
  }
  CHECK(warned);
  CHECK(ejected);
  CHECK(ledger.exclusions().count("c") == 1);
  // Samples while ejected are ignored; beats keep last_beat fresh.
  beat("c", 9, 10.0, 100);
  CHECK(ledger.exclusions().count("c") == 1);
  // Before the probation window: no readmission.
  auto evs = ledger.tick(base + Millis(500), 50000);
  CHECK(evs.empty());
  // After probation_ms of fresh beats: readmitted on probation.
  ledger.on_heartbeat("c", nullptr, base + Millis(1200));
  evs = ledger.tick(base + Millis(1200), 50000);
  CHECK(evs.size() == 1 && evs[0].get("kind").as_string() == "readmit");
  CHECK(ledger.exclusions().count("c") == 0);
  // Clean post-recovery samples walk probation back to ok.
  for (int64_t step = 20; step <= 26; ++step) {
    beat("a", step, 1.0, 1300 + step);
    beat("b", step, 1.0, 1300 + step);
    beat("c", step, 1.0, 1300 + step);
  }
  Json rj = ledger.replica_json("c");
  CHECK(rj.get("state").as_string() == "ok");
  CHECK(rj.get("ejections").as_int() == 1);
  CHECK(rj.get("readmissions").as_int() == 1);
}

static void test_health_never_ejects_below_min_replicas() {
  HealthOpts opts;
  opts.mode = "eject";
  opts.min_samples = 3;
  opts.eject_steps = 2;
  HealthLedger ledger(opts, 5000, /*min_replicas=*/2);
  TimePoint base = Clock::now();
  auto beat = [&](const std::string& rid, int64_t step, double step_s) {
    Json t = Json::object();
    t["step"] = step;
    t["step_s"] = step_s;
    t["wire_s"] = 0.0;
    return ledger.on_heartbeat(rid, &t, base + Millis(step * 10));
  };
  // 2-replica fleet with min_replicas=2: the straggler can never be
  // ejected (and the symmetric 2-point score stays tiny anyway).
  bool ejected = false;
  for (int64_t step = 1; step <= 10; ++step) {
    beat("a", step, 1.0);
    for (const auto& e : beat("b", step, 10.0))
      if (e.get("kind").as_string() == "eject") ejected = true;
  }
  CHECK(!ejected);
  CHECK(ledger.exclusions().empty());
}

static void test_quorum_excluded_replica() {
  LighthouseOpts opts;
  opts.min_replicas = 1;
  opts.join_timeout_ms = 60000;
  opts.heartbeat_timeout_ms = 5000;
  TimePoint now = Clock::now();
  LighthouseState state;
  for (const auto& id : {"a", "b", "c"}) {
    state.participants[id] = MemberDetails{now, member(id)};
    state.heartbeats[id] = now;
  }
  QuorumSnapshot prev;
  prev.quorum_id = 1;
  prev.participants = {member("a"), member("b"), member("c")};
  state.prev_quorum = prev;
  state.excluded.insert("c");
  // "c" is fresh but ejected: the quorum must form without it, and "c"
  // must not veto the all-joined check (no join-timeout stall).
  auto [met, reason] = quorum_compute(now, state, opts);
  CHECK(met.has_value());
  CHECK(met->size() == 2);
  for (const auto& m : *met) CHECK(m.replica_id != "c");
}

// ------------------------------------------------------ heartbeat skew sign
static void test_heartbeat_skew_sign() {
  // Fake lighthouse answering the real beat loop with a fabricated
  // server_ms 5s in the past: a lighthouse clock 5s BEHIND is this
  // replica running 5s AHEAD, so the estimate must come out POSITIVE
  // (replica-minus-lighthouse) — the sign merge_traces subtracts to land
  // replica timestamps on the lighthouse's clock. A flipped estimator
  // would double the skew error in merged fleet timelines.
  RpcServer fake("127.0.0.1:0",
                 [](const std::string& m, const Json&, TimePoint) {
                   CHECK(m == "heartbeat");
                   Json out = Json::object();
                   out["server_ms"] = epoch_millis_now() - 5000;
                   return out;
                 });
  ManagerOpts mo;
  mo.replica_id = "skew_pin";
  mo.lighthouse_addr = "127.0.0.1:" + std::to_string(fake.port());
  mo.hostname = "127.0.0.1";
  mo.bind = "127.0.0.1:0";
  mo.heartbeat_interval_ms = 20;
  ManagerServer mgr(mo);
  double skew = 0.0, last = 0.0;
  int64_t samples = 0;
  for (int i = 0; i < 500 && samples < 1; ++i) {
    Json j = Json::parse(mgr.clock_skew_json());
    samples = j.get("samples").as_int();
    skew = j.get("skew_ms").as_double();
    last = j.get("last_skew_ms").as_double();
    std::this_thread::sleep_for(Millis(10));
  }
  CHECK(samples >= 1);
  // Loopback RTT is ~0; allow generous slack for a loaded CI host.
  CHECK(skew > 4000.0 && skew < 6000.0);
  CHECK(last > 4000.0 && last < 6000.0);
  mgr.shutdown();
  fake.shutdown();
}

int main() {
  test_quorum_fast_path();
  test_quorum_join_timeout_straggler();
  test_quorum_min_replicas();
  test_quorum_expired_heartbeat();
  test_quorum_split_brain();
  test_quorum_shrink_only();
  test_results_first_step_force_recover();
  test_results_behind_replica_heals();
  test_results_store_spread_across_group_ranks();
  test_results_not_in_quorum();
  test_results_commit_failures_max();
  test_health_scores_straggler();
  test_health_ledger_eject_and_readmit();
  test_health_never_ejects_below_min_replicas();
  test_quorum_excluded_replica();
  test_wire_echo_and_timeout();
  test_kvstore();
  test_lighthouse_manager_e2e();
  test_heartbeat_skew_sign();
  if (failures == 0) {
    std::printf("native_test: all tests passed\n");
    return 0;
  }
  std::printf("native_test: %d failures\n", failures);
  return 1;
}
