// Lighthouse aggregator: the pod-level tier of the two-level control plane.
//
// A flat fleet points every replica-group manager straight at the root
// lighthouse — N connections, N heartbeat RPCs per beat interval, N blocked
// quorum waits. That is the wall between "6 replicas on loopback" and a
// production fleet (Fault Tolerant HSDP on 100k GPUs runs per-step quorum
// only because heartbeats fan in hierarchically). An Aggregator fronts one
// pod of replicas and speaks the SAME wire protocol the lighthouse does
// ("heartbeat", "quorum", /status over HTTP), so a replica points at it via
// TORCHFT_LIGHTHOUSE_AGGREGATOR with zero Manager API changes. Upstream it
// collapses the pod into ONE delta-encoded "agg_tick" RPC per tick:
//
//   - liveness: the live replica-id set, sent in full only when it CHANGES
//     ("beats_same" otherwise) — the aggregator vouches for pod freshness;
//   - telemetry: forwarded only for replicas whose reported step advanced
//     since the last acked tick (the flat protocol re-sends the full
//     payload on every beat);
//   - quorum joins: pending requesters ride the same tick RPC; results fan
//     back out to the blocked pod RPCs from the tick response.
//
// Every frame carries (agg_id, epoch, seq): the root rejects stale deltas
// from a previous incarnation after an aggregator restart. If the upstream
// link dies the pod's managers fail over to direct-to-root mode on their
// own (manager_server.cc) — the aggregator itself just keeps retrying.
#pragma once

#include <condition_variable>
#include <map>
#include <memory>
#include <mutex>
#include <set>
#include <thread>

#include "quorum.h"
#include "wire.h"

namespace tft {

struct AggregatorOpts {
  std::string root_addr;          // upstream lighthouse "host:port"
  std::string agg_id;             // empty -> derived from bind address
  int64_t tick_ms = 100;          // upstream batching cadence
  int64_t heartbeat_timeout_ms = 5000;  // pod-liveness horizon (match root)
  int64_t connect_timeout_ms = 10000;
};

class Aggregator {
 public:
  Aggregator(const std::string& bind, AggregatorOpts opts);
  ~Aggregator();

  int port() const { return server_->port(); }
  std::string address() const;
  const std::string& agg_id() const { return agg_id_; }
  void shutdown();

  // Local pod + upstream view (also served at GET /status): pod size, live
  // set, pending joiners, upstream tick counters, last error.
  Json status_json();

 private:
  Json handle(const std::string& method, const Json& params, TimePoint deadline);
  std::tuple<std::string, std::string, std::string> handle_http(
      const std::string& method, const std::string& path);

  Json rpc_heartbeat(const Json& params);
  Json rpc_quorum(const Json& params, TimePoint deadline);

  void tick_loop();
  // Build the delta frame under mu_ (returns null when nothing to send and
  // the live set is unchanged — a keepalive frame is still sent so the
  // root's aggregator registry stays fresh).
  Json build_tick_frame_locked();
  void apply_tick_response_locked(const Json& resp);

  struct PodReplica {
    TimePoint last_beat{};
    Json telemetry;               // latest payload from the pod beat
    int64_t telemetry_step = -1;  // step of `telemetry`
    int64_t forwarded_step = -1;  // last step acked upstream (delta cursor)
    Json health;                  // cached root health summary (fanned back)
  };

  struct PendingJoiner {
    QuorumMember member;
    TimePoint deadline;  // drop expired joiners so the root stops waiting
  };

  AggregatorOpts opts_;
  std::string agg_id_;
  int64_t epoch_ = 0;  // epoch_millis at construction; restarts bump it
  int64_t seq_ = 0;    // per-epoch tick sequence

  std::mutex mu_;
  std::condition_variable quorum_cv_;  // pod quorum fan-out
  std::condition_variable tick_cv_;    // wake the tick loop early on joins
  bool tick_requested_ = false;
  std::map<std::string, PodReplica> pod_;
  std::map<std::string, PendingJoiner> joiners_;
  std::set<std::string> last_live_sent_;  // delta cursor for the live set
  std::set<std::string> pending_live_;    // live set of the in-flight frame
  bool last_tick_ok_ = false;
  std::string last_error_;
  uint64_t ticks_ok_ = 0;
  uint64_t ticks_failed_ = 0;
  uint64_t upstream_bytes_ = 0;  // serialized agg_tick param bytes sent
  int64_t root_quorum_gen_ = 0;  // root's broadcast generation we've seen
  uint64_t quorum_gen_ = 0;      // local fan-out generation
  std::optional<QuorumSnapshot> latest_quorum_;
  // Newest policy frame seen on a tick response; fanned out to the pod on
  // heartbeat replies. Null until the root publishes one.
  Json policy_frame_;

  std::atomic<bool> running_{true};
  std::unique_ptr<RpcServer> server_;
  std::unique_ptr<RpcClient> root_client_;
  std::thread tick_thread_;
};

}  // namespace tft
