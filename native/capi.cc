// C API for Python ctypes bindings (torchft_tpu/coordination.py).
// Role-equivalent of the reference's pyo3 extension module src/lib.rs:
// server lifecycles + blocking client RPCs. ctypes releases the GIL around
// every call, matching the reference's py.allow_threads behavior.
//
// Conventions: returns int status (see TFT_* codes); out-strings are
// malloc'd and must be freed with tft_free.

#include <cstdlib>
#include <cstring>
#include <string>

#include "kvstore.h"
#include "lighthouse.h"
#include "manager_server.h"
#include "quorum.h"
#include "wire.h"

using namespace tft;

extern "C" {

enum {
  TFT_OK = 0,
  TFT_TIMEOUT = 1,
  TFT_ERROR = 2,
  TFT_NOT_FOUND = 3,
  TFT_INVALID = 4,
  TFT_UNAVAILABLE = 5,
};

static char* dup_str(const std::string& s) {
  char* out = static_cast<char*>(malloc(s.size() + 1));
  memcpy(out, s.data(), s.size());
  out[s.size()] = '\0';
  return out;
}

static int status_of(const RpcError& e) {
  if (e.code == "timeout") return TFT_TIMEOUT;
  if (e.code == "not_found") return TFT_NOT_FOUND;
  if (e.code == "invalid") return TFT_INVALID;
  if (e.code == "unavailable") return TFT_UNAVAILABLE;
  return TFT_ERROR;
}

#define TFT_TRY(...)                                    \
  try {                                                 \
    __VA_ARGS__;                                        \
  } catch (const RpcError& e) {                         \
    if (err) *err = dup_str(e.what());                  \
    return status_of(e);                                \
  } catch (const std::exception& e) {                   \
    if (err) *err = dup_str(e.what());                  \
    std::string msg = e.what();                         \
    return msg.find("timed out") != std::string::npos   \
               ? TFT_TIMEOUT                            \
               : TFT_ERROR;                             \
  }

void tft_free(char* p) { free(p); }

// ---------------------------------------------------------------- lighthouse
int tft_lighthouse_new(const char* bind, int64_t min_replicas,
                       int64_t join_timeout_ms, int64_t quorum_tick_ms,
                       int64_t heartbeat_timeout_ms, void** out, char** err) {
  TFT_TRY({
    LighthouseOpts opts;
    opts.min_replicas = min_replicas;
    opts.join_timeout_ms = join_timeout_ms;
    opts.quorum_tick_ms = quorum_tick_ms;
    opts.heartbeat_timeout_ms = heartbeat_timeout_ms;
    *out = new Lighthouse(bind, opts);
    return TFT_OK;
  })
}

char* tft_lighthouse_address(void* h) {
  return dup_str(static_cast<Lighthouse*>(h)->address());
}
int tft_lighthouse_port(void* h) { return static_cast<Lighthouse*>(h)->port(); }
void tft_lighthouse_shutdown(void* h) {
  static_cast<Lighthouse*>(h)->shutdown();
}
void tft_lighthouse_free(void* h) { delete static_cast<Lighthouse*>(h); }

// ------------------------------------------------------------------- manager
int tft_manager_new(const char* opts_json, void** out, char** err) {
  TFT_TRY({
    Json j = Json::parse(opts_json);
    ManagerOpts opts;
    opts.replica_id = j.get("replica_id").as_string();
    opts.lighthouse_addr = j.get("lighthouse_addr").as_string();
    opts.hostname = j.get_or("hostname", Json("")).as_string();
    opts.bind = j.get_or("bind", Json("0.0.0.0:0")).as_string();
    opts.store_addr = j.get_or("store_addr", Json("")).as_string();
    opts.world_size = j.get_or("world_size", Json(int64_t{1})).as_int();
    opts.heartbeat_interval_ms =
        j.get_or("heartbeat_interval_ms", Json(int64_t{100})).as_int();
    opts.connect_timeout_ms =
        j.get_or("connect_timeout_ms", Json(int64_t{10000})).as_int();
    opts.quorum_retries = j.get_or("quorum_retries", Json(int64_t{0})).as_int();
    *out = new ManagerServer(opts);
    return TFT_OK;
  })
}

char* tft_manager_address(void* h) {
  return dup_str(static_cast<ManagerServer*>(h)->address());
}
int tft_manager_port(void* h) { return static_cast<ManagerServer*>(h)->port(); }
void tft_manager_shutdown(void* h) {
  static_cast<ManagerServer*>(h)->shutdown();
}
void tft_manager_free(void* h) { delete static_cast<ManagerServer*>(h); }

// ------------------------------------------------------------------- clients
// Client handles own a persistent RpcClient: its cached keep-alive
// connection is reused across calls (reconnecting if stale), and concurrent
// calls from other threads transparently fall back to one-shot connections.
struct ClientHandle {
  RpcClient client;
  ClientHandle(const char* addr, int64_t connect_timeout_ms)
      : client(addr, Millis(connect_timeout_ms)) {}
};

int tft_client_new(const char* addr, int64_t connect_timeout_ms, void** out,
                   char** err) {
  TFT_TRY({
    *out = new ClientHandle(addr, connect_timeout_ms);
    return TFT_OK;
  })
}
void tft_client_free(void* h) { delete static_cast<ClientHandle*>(h); }

// Generic call: params/result as JSON strings. Used by Python for every RPC.
int tft_client_call(void* h, const char* method, const char* params_json,
                    int64_t timeout_ms, char** result, char** err) {
  TFT_TRY({
    auto* c = static_cast<ClientHandle*>(h);
    Json params = Json::parse(params_json);
    Json r = c->client.call(method, params, Millis(timeout_ms));
    if (result) *result = dup_str(r.dump());
    return TFT_OK;
  })
}

// ------------------------------------------------------------------- kvstore
int tft_kvstore_new(const char* bind, void** out, char** err) {
  TFT_TRY({
    *out = new KvStoreServer(bind);
    return TFT_OK;
  })
}
int tft_kvstore_port(void* h) { return static_cast<KvStoreServer*>(h)->port(); }
void tft_kvstore_shutdown(void* h) {
  static_cast<KvStoreServer*>(h)->shutdown();
}
void tft_kvstore_free(void* h) { delete static_cast<KvStoreServer*>(h); }

// ------------------------------------------------------- pure quorum logic
// Exposed for unit tests (reference pattern: src/lighthouse.rs:627-1071 and
// src/manager.rs:881-1108 test these as pure functions).

// state_json: {"participants": [{"member": {...}, "joined_ms_ago": N}],
//              "heartbeats": {"rid": age_ms}, "prev_quorum": {...}|null,
//              "quorum_id": N}
int tft_quorum_compute(const char* state_json, const char* opts_json,
                       char** result, char** err) {
  TFT_TRY({
    Json js = Json::parse(state_json);
    Json jo = Json::parse(opts_json);
    LighthouseOpts opts;
    opts.min_replicas = jo.get_or("min_replicas", Json(int64_t{1})).as_int();
    opts.join_timeout_ms =
        jo.get_or("join_timeout_ms", Json(int64_t{60000})).as_int();
    opts.heartbeat_timeout_ms =
        jo.get_or("heartbeat_timeout_ms", Json(int64_t{5000})).as_int();

    TimePoint now = Clock::now();
    LighthouseState state;
    state.quorum_id = js.get_or("quorum_id", Json(int64_t{0})).as_int();
    // Bind to a named value: get_or returns a temporary, and a range-for over
    // a reference into it would dangle.
    Json participants = js.get_or("participants", Json::array());
    for (const auto& p : participants.as_array()) {
      MemberDetails d;
      d.member = QuorumMember::from_json(p.get("member"));
      d.joined = now - Millis(p.get_or("joined_ms_ago", Json(int64_t{0})).as_int());
      state.participants[d.member.replica_id] = d;
    }
    if (js.contains("heartbeats")) {
      for (const auto& [rid, age] : js.get("heartbeats").as_object())
        state.heartbeats[rid] = now - Millis(age.as_int());
    }
    if (js.contains("prev_quorum") && !js.get("prev_quorum").is_null())
      state.prev_quorum = QuorumSnapshot::from_json(js.get("prev_quorum"));

    auto [met, reason] = quorum_compute(now, state, opts);
    Json out = Json::object();
    out["reason"] = reason;
    if (met) {
      Json parts = Json::array();
      for (const auto& m : *met) parts.push_back(m.to_json());
      out["participants"] = parts;
    } else {
      out["participants"] = Json();
    }
    if (result) *result = dup_str(out.dump());
    return TFT_OK;
  })
}

int tft_compute_quorum_results(const char* replica_id, int64_t group_rank,
                               const char* quorum_json, int init_sync,
                               char** result, char** err) {
  TFT_TRY({
    QuorumSnapshot q = QuorumSnapshot::from_json(Json::parse(quorum_json));
    ManagerQuorumResult r =
        compute_quorum_results(replica_id, group_rank, q, init_sync != 0);
    if (result) *result = dup_str(r.to_json().dump());
    return TFT_OK;
  })
}

}  // extern "C"
