// C API for Python ctypes bindings (torchft_tpu/coordination.py).
// Role-equivalent of the reference's pyo3 extension module src/lib.rs:
// server lifecycles + blocking client RPCs. ctypes releases the GIL around
// every call, matching the reference's py.allow_threads behavior.
//
// Conventions: returns int status (see TFT_* codes); out-strings are
// malloc'd and must be freed with tft_free.

#include <cstdlib>
#include <cstring>
#include <string>

#include "aggregator.h"
#include "healthwatch.h"
#include "history.h"
#include "kvstore.h"
#include "lighthouse.h"
#include "manager_server.h"
#include "quorum.h"
#include "wire.h"

using namespace tft;

extern "C" {

enum {
  TFT_OK = 0,
  TFT_TIMEOUT = 1,
  TFT_ERROR = 2,
  TFT_NOT_FOUND = 3,
  TFT_INVALID = 4,
  TFT_UNAVAILABLE = 5,
};

static char* dup_str(const std::string& s) {
  char* out = static_cast<char*>(malloc(s.size() + 1));
  memcpy(out, s.data(), s.size());
  out[s.size()] = '\0';
  return out;
}

static int status_of(const RpcError& e) {
  if (e.code == "timeout") return TFT_TIMEOUT;
  if (e.code == "not_found") return TFT_NOT_FOUND;
  if (e.code == "invalid") return TFT_INVALID;
  if (e.code == "unavailable") return TFT_UNAVAILABLE;
  return TFT_ERROR;
}

#define TFT_TRY(...)                                    \
  try {                                                 \
    __VA_ARGS__;                                        \
  } catch (const RpcError& e) {                         \
    if (err) *err = dup_str(e.what());                  \
    return status_of(e);                                \
  } catch (const std::exception& e) {                   \
    if (err) *err = dup_str(e.what());                  \
    std::string msg = e.what();                         \
    return msg.find("timed out") != std::string::npos   \
               ? TFT_TIMEOUT                            \
               : TFT_ERROR;                             \
  }

void tft_free(char* p) { free(p); }

// ---------------------------------------------------------------- lighthouse
int tft_lighthouse_new(const char* bind, int64_t min_replicas,
                       int64_t join_timeout_ms, int64_t quorum_tick_ms,
                       int64_t heartbeat_timeout_ms, void** out, char** err) {
  TFT_TRY({
    LighthouseOpts opts;
    opts.min_replicas = min_replicas;
    opts.join_timeout_ms = join_timeout_ms;
    opts.quorum_tick_ms = quorum_tick_ms;
    opts.heartbeat_timeout_ms = heartbeat_timeout_ms;
    *out = new Lighthouse(bind, opts);
    return TFT_OK;
  })
}

// JSON-opts constructor (supersedes the scalar one above, which is kept for
// ABI compat): {"bind": ..., "min_replicas": N, "join_timeout_ms": N,
// "quorum_tick_ms": N, "heartbeat_timeout_ms": N, "health": {...}} — the
// "health" object is HealthOpts (healthwatch.h), absent -> defaults
// (observe mode).
int tft_lighthouse_new_v2(const char* opts_json, void** out, char** err) {
  TFT_TRY({
    Json j = Json::parse(opts_json);
    LighthouseOpts opts;
    std::string bind = j.get_or("bind", Json("0.0.0.0:0")).as_string();
    opts.min_replicas = j.get_or("min_replicas", Json(int64_t{1})).as_int();
    opts.join_timeout_ms =
        j.get_or("join_timeout_ms", Json(int64_t{60000})).as_int();
    opts.quorum_tick_ms =
        j.get_or("quorum_tick_ms", Json(int64_t{100})).as_int();
    opts.heartbeat_timeout_ms =
        j.get_or("heartbeat_timeout_ms", Json(int64_t{5000})).as_int();
    opts.history_path = j.get_or("history_path", Json("")).as_string();
    opts.policy_ring = j.get_or("policy_ring", Json(int64_t{0})).as_int();
    opts.metrics_per_replica_limit =
        j.get_or("metrics_per_replica_limit", Json(int64_t{64})).as_int();
    HealthOpts health =
        HealthOpts::from_json(j.get_or("health", Json::object()));
    *out = new Lighthouse(bind, opts, health);
    return TFT_OK;
  })
}

// ---- policy plane: in-process control surface on the lighthouse handle.
// These are C-API calls for the co-located policy engine, NOT wire RPCs —
// the wire protocol stays at its five methods; frames ride existing
// heartbeat/agg_tick replies.
int tft_lighthouse_set_policy(void* h, const char* frame_json, char** err) {
  TFT_TRY({
    static_cast<Lighthouse*>(h)->set_policy(Json::parse(frame_json));
    return TFT_OK;
  })
}

char* tft_lighthouse_policy(void* h) {
  return dup_str(static_cast<Lighthouse*>(h)->policy_json());
}

char* tft_lighthouse_drain_events(void* h) {
  return dup_str(static_cast<Lighthouse*>(h)->drain_events());
}

int tft_lighthouse_retune_health(void* h, const char* partial_json, char** out,
                                 char** err) {
  TFT_TRY({
    *out = dup_str(
        static_cast<Lighthouse*>(h)->retune_health(Json::parse(partial_json)));
    return TFT_OK;
  })
}

char* tft_lighthouse_address(void* h) {
  return dup_str(static_cast<Lighthouse*>(h)->address());
}
int tft_lighthouse_port(void* h) { return static_cast<Lighthouse*>(h)->port(); }
void tft_lighthouse_shutdown(void* h) {
  static_cast<Lighthouse*>(h)->shutdown();
}
void tft_lighthouse_free(void* h) { delete static_cast<Lighthouse*>(h); }

// ---------------------------------------------------------------- aggregator
// Pod-level lighthouse aggregator (aggregator.h). opts_json: {"bind": ...,
// "root_addr": ..., "agg_id": ...?, "tick_ms": N, "heartbeat_timeout_ms": N,
// "connect_timeout_ms": N}.
int tft_aggregator_new(const char* opts_json, void** out, char** err) {
  TFT_TRY({
    Json j = Json::parse(opts_json);
    AggregatorOpts opts;
    std::string bind = j.get_or("bind", Json("0.0.0.0:0")).as_string();
    opts.root_addr = j.get("root_addr").as_string();
    opts.agg_id = j.get_or("agg_id", Json("")).as_string();
    opts.tick_ms = j.get_or("tick_ms", Json(int64_t{100})).as_int();
    opts.heartbeat_timeout_ms =
        j.get_or("heartbeat_timeout_ms", Json(int64_t{5000})).as_int();
    opts.connect_timeout_ms =
        j.get_or("connect_timeout_ms", Json(int64_t{10000})).as_int();
    *out = new Aggregator(bind, opts);
    return TFT_OK;
  })
}

char* tft_aggregator_address(void* h) {
  return dup_str(static_cast<Aggregator*>(h)->address());
}
int tft_aggregator_port(void* h) { return static_cast<Aggregator*>(h)->port(); }
char* tft_aggregator_status(void* h) {
  return dup_str(static_cast<Aggregator*>(h)->status_json().dump());
}
void tft_aggregator_shutdown(void* h) {
  static_cast<Aggregator*>(h)->shutdown();
}
void tft_aggregator_free(void* h) { delete static_cast<Aggregator*>(h); }

// ------------------------------------------------------------------- manager
int tft_manager_new(const char* opts_json, void** out, char** err) {
  TFT_TRY({
    Json j = Json::parse(opts_json);
    ManagerOpts opts;
    opts.replica_id = j.get("replica_id").as_string();
    opts.lighthouse_addr = j.get("lighthouse_addr").as_string();
    opts.hostname = j.get_or("hostname", Json("")).as_string();
    opts.bind = j.get_or("bind", Json("0.0.0.0:0")).as_string();
    opts.store_addr = j.get_or("store_addr", Json("")).as_string();
    opts.world_size = j.get_or("world_size", Json(int64_t{1})).as_int();
    opts.heartbeat_interval_ms =
        j.get_or("heartbeat_interval_ms", Json(int64_t{100})).as_int();
    opts.connect_timeout_ms =
        j.get_or("connect_timeout_ms", Json(int64_t{10000})).as_int();
    opts.quorum_retries = j.get_or("quorum_retries", Json(int64_t{0})).as_int();
    opts.aggregator_addr = j.get_or("aggregator_addr", Json("")).as_string();
    *out = new ManagerServer(opts);
    return TFT_OK;
  })
}

char* tft_manager_control_status(void* h) {
  return dup_str(static_cast<ManagerServer*>(h)->control_status_json());
}

int tft_manager_publish_telemetry(void* h, const char* telemetry_json,
                                  char** err) {
  TFT_TRY({
    static_cast<ManagerServer*>(h)->publish_telemetry(telemetry_json);
    return TFT_OK;
  })
}

char* tft_manager_health(void* h) {
  return dup_str(static_cast<ManagerServer*>(h)->health_json());
}

char* tft_manager_policy(void* h) {
  return dup_str(static_cast<ManagerServer*>(h)->policy_json());
}

char* tft_manager_clock_skew(void* h) {
  return dup_str(static_cast<ManagerServer*>(h)->clock_skew_json());
}

char* tft_manager_address(void* h) {
  return dup_str(static_cast<ManagerServer*>(h)->address());
}
int tft_manager_port(void* h) { return static_cast<ManagerServer*>(h)->port(); }
void tft_manager_shutdown(void* h) {
  static_cast<ManagerServer*>(h)->shutdown();
}
void tft_manager_free(void* h) { delete static_cast<ManagerServer*>(h); }

// ------------------------------------------------------------------- clients
// Client handles own a persistent RpcClient: its cached keep-alive
// connection is reused across calls (reconnecting if stale), and concurrent
// calls from other threads transparently fall back to one-shot connections.
struct ClientHandle {
  RpcClient client;
  ClientHandle(const char* addr, int64_t connect_timeout_ms)
      : client(addr, Millis(connect_timeout_ms)) {}
};

int tft_client_new(const char* addr, int64_t connect_timeout_ms, void** out,
                   char** err) {
  TFT_TRY({
    *out = new ClientHandle(addr, connect_timeout_ms);
    return TFT_OK;
  })
}
void tft_client_free(void* h) { delete static_cast<ClientHandle*>(h); }

// Generic call: params/result as JSON strings. Used by Python for every RPC.
int tft_client_call(void* h, const char* method, const char* params_json,
                    int64_t timeout_ms, char** result, char** err) {
  TFT_TRY({
    auto* c = static_cast<ClientHandle*>(h);
    Json params = Json::parse(params_json);
    Json r = c->client.call(method, params, Millis(timeout_ms));
    if (result) *result = dup_str(r.dump());
    return TFT_OK;
  })
}

// ------------------------------------------------------------------- kvstore
int tft_kvstore_new(const char* bind, void** out, char** err) {
  TFT_TRY({
    *out = new KvStoreServer(bind);
    return TFT_OK;
  })
}
int tft_kvstore_port(void* h) { return static_cast<KvStoreServer*>(h)->port(); }
void tft_kvstore_shutdown(void* h) {
  static_cast<KvStoreServer*>(h)->shutdown();
}
void tft_kvstore_free(void* h) { delete static_cast<KvStoreServer*>(h); }

// ------------------------------------------------------- pure quorum logic
// Exposed for unit tests (reference pattern: src/lighthouse.rs:627-1071 and
// src/manager.rs:881-1108 test these as pure functions).

// state_json: {"participants": [{"member": {...}, "joined_ms_ago": N}],
//              "heartbeats": {"rid": age_ms}, "prev_quorum": {...}|null,
//              "quorum_id": N}
int tft_quorum_compute(const char* state_json, const char* opts_json,
                       char** result, char** err) {
  TFT_TRY({
    Json js = Json::parse(state_json);
    Json jo = Json::parse(opts_json);
    LighthouseOpts opts;
    opts.min_replicas = jo.get_or("min_replicas", Json(int64_t{1})).as_int();
    opts.join_timeout_ms =
        jo.get_or("join_timeout_ms", Json(int64_t{60000})).as_int();
    opts.heartbeat_timeout_ms =
        jo.get_or("heartbeat_timeout_ms", Json(int64_t{5000})).as_int();

    TimePoint now = Clock::now();
    LighthouseState state;
    state.quorum_id = js.get_or("quorum_id", Json(int64_t{0})).as_int();
    // Bind to a named value: get_or returns a temporary, and a range-for over
    // a reference into it would dangle.
    Json participants = js.get_or("participants", Json::array());
    for (const auto& p : participants.as_array()) {
      MemberDetails d;
      d.member = QuorumMember::from_json(p.get("member"));
      d.joined = now - Millis(p.get_or("joined_ms_ago", Json(int64_t{0})).as_int());
      state.participants[d.member.replica_id] = d;
    }
    if (js.contains("heartbeats")) {
      for (const auto& [rid, age] : js.get("heartbeats").as_object())
        state.heartbeats[rid] = now - Millis(age.as_int());
    }
    if (js.contains("prev_quorum") && !js.get("prev_quorum").is_null())
      state.prev_quorum = QuorumSnapshot::from_json(js.get("prev_quorum"));
    if (js.contains("excluded")) {
      for (const auto& rid : js.get("excluded").as_array())
        state.excluded.insert(rid.as_string());
    }

    auto [met, reason] = quorum_compute(now, state, opts);
    Json out = Json::object();
    out["reason"] = reason;
    if (met) {
      Json parts = Json::array();
      for (const auto& m : *met) parts.push_back(m.to_json());
      out["participants"] = parts;
    } else {
      out["participants"] = Json();
    }
    if (result) *result = dup_str(out.dump());
    return TFT_OK;
  })
}

// ------------------------------------------------------- pure health logic
// Parity hooks for tests: torchft_tpu/healthwatch.py carries the canonical
// Python scoring/policy spec, and tests drive the SAME synthetic inputs
// through these to pin the native ledger to it.

// windows_json: {"rid": [samples...]} -> {"rid": score}
int tft_health_scores(const char* windows_json, const char* opts_json,
                      char** result, char** err) {
  TFT_TRY({
    Json jw = Json::parse(windows_json);
    HealthOpts opts = HealthOpts::from_json(Json::parse(opts_json));
    std::map<std::string, std::vector<double>> windows;
    for (const auto& [rid, arr] : jw.as_object()) {
      std::vector<double> w;
      for (const auto& v : arr.as_array()) w.push_back(v.as_double());
      windows[rid] = w;
    }
    auto scores = straggler_scores(windows, opts);
    Json out = Json::object();
    for (const auto& [rid, s] : scores) out[rid] = s;
    if (result) *result = dup_str(out.dump());
    return TFT_OK;
  })
}

// Deterministic ledger replay on a synthetic clock. opts_json: HealthOpts
// fields plus "heartbeat_timeout_ms" and "min_replicas". script_json: array
// of {"t_ms": N, "replica_id": ..., "telemetry": {...}?} beats and
// {"t_ms": N, "tick": true} ticks, applied in order.
int tft_health_replay(const char* script_json, const char* opts_json,
                      char** result, char** err) {
  TFT_TRY({
    Json js = Json::parse(script_json);
    Json jo = Json::parse(opts_json);
    HealthOpts opts = HealthOpts::from_json(jo);
    int64_t hb_timeout =
        jo.get_or("heartbeat_timeout_ms", Json(int64_t{5000})).as_int();
    int64_t min_replicas =
        jo.get_or("min_replicas", Json(int64_t{1})).as_int();
    HealthLedger ledger(opts, hb_timeout, min_replicas);

    TimePoint base = Clock::now();
    int64_t last_t = 0;
    Json events = Json::array();
    for (const auto& entry : js.as_array()) {
      int64_t t_ms = entry.get_or("t_ms", Json(int64_t{0})).as_int();
      last_t = t_ms;
      TimePoint now = base + Millis(t_ms);
      std::vector<Json> evs;
      if (entry.get_or("tick", Json(false)).as_bool()) {
        evs = ledger.tick(
            now, entry.get_or("prune_after_ms", Json(10 * hb_timeout)).as_int());
      } else {
        std::string rid = entry.get("replica_id").as_string();
        const Json* telemetry = nullptr;
        Json t;
        if (entry.contains("telemetry") && !entry.get("telemetry").is_null()) {
          t = entry.get("telemetry");
          telemetry = &t;
        }
        evs = ledger.on_heartbeat(rid, telemetry, now);
      }
      for (auto& e : evs) {
        e["t_ms"] = t_ms;
        events.push_back(e);
      }
    }
    Json out = Json::object();
    out["events"] = events;
    out["ledger"] = ledger.to_json(base + Millis(last_t));
    Json ex = Json::array();
    for (const auto& rid : ledger.exclusions()) ex.push_back(rid);
    out["excluded"] = ex;
    if (result) *result = dup_str(out.dump());
    return TFT_OK;
  })
}

// ------------------------------------------------------ recorded history
// Read path for the lighthouse's history JSONL (history.h). Takes the file
// CONTENT (not a path) so tests and remote tooling can feed bytes from
// anywhere; returns {"events": [...], "summary": {...}} where summary is
// the pure history_fold — mirrored by torchft_tpu.tracing.history_fold,
// parity pinned by test (same convention as tft_health_replay).
int tft_history_replay(const char* jsonl, char** result, char** err) {
  TFT_TRY({
    Json events = Json::array();
    std::string text(jsonl);
    size_t pos = 0;
    while (pos <= text.size()) {
      size_t nl = text.find('\n', pos);
      size_t end = nl == std::string::npos ? text.size() : nl;
      std::string line = text.substr(pos, end - pos);
      pos = end + 1;
      // skip blank lines (trailing newline, hand-edited files)
      if (line.find_first_not_of(" \t\r") == std::string::npos) {
        if (nl == std::string::npos) break;
        continue;
      }
      events.push_back(Json::parse(line));
      if (nl == std::string::npos) break;
    }
    Json out = Json::object();
    out["events"] = events;
    out["summary"] = history_fold(events);
    if (result) *result = dup_str(out.dump());
    return TFT_OK;
  })
}

int tft_compute_quorum_results(const char* replica_id, int64_t group_rank,
                               const char* quorum_json, int init_sync,
                               char** result, char** err) {
  TFT_TRY({
    QuorumSnapshot q = QuorumSnapshot::from_json(Json::parse(quorum_json));
    ManagerQuorumResult r =
        compute_quorum_results(replica_id, group_rank, q, init_sync != 0);
    if (result) *result = dup_str(r.to_json().dump());
    return TFT_OK;
  })
}

}  // extern "C"
