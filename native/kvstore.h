// TCP key-value store for rendezvous: the TPU-native replacement for the
// reference's PyTorch TCPStore (used for manager-address discovery,
// manager.py:333-337, and per-quorum communicator bootstrap with prefixes
// "{store}/torchft/{quorum_id}/{group_rank}", manager.py:703-705).
// Blocking get with timeout + atomic add, over the framed-JSON wire protocol.
// Values are opaque strings (clients base64-encode binary payloads).
#pragma once

#include <condition_variable>
#include <map>
#include <memory>
#include <mutex>

#include "wire.h"

namespace tft {

class KvStoreServer {
 public:
  explicit KvStoreServer(const std::string& bind);
  ~KvStoreServer();

  int port() const { return server_->port(); }
  void shutdown();

 private:
  Json handle(const std::string& method, const Json& params, TimePoint deadline);

  std::mutex mu_;
  std::condition_variable cv_;
  std::map<std::string, std::string> data_;
  std::atomic<bool> running_{true};
  std::unique_ptr<RpcServer> server_;
};

}  // namespace tft
