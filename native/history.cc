#include "history.h"

#include <algorithm>
#include <set>

#include "quorum.h"  // epoch_millis_now

namespace tft {

HistoryStore::HistoryStore(std::string path) : path_(std::move(path)) {
  if (path_.empty()) return;
  out_.open(path_, std::ios::out | std::ios::app);
  if (!out_.is_open()) path_.clear();  // unwritable -> disabled, not fatal
}

void HistoryStore::enable_ring(int64_t cap) {
  std::lock_guard<std::mutex> lk(mu_);
  ring_cap_ = cap > 0 ? cap : 0;
  if (ring_cap_ == 0) ring_.clear();
}

bool HistoryStore::ring_enabled() const {
  std::lock_guard<std::mutex> lk(mu_);
  return ring_cap_ > 0;
}

std::vector<Json> HistoryStore::drain_ring() {
  std::lock_guard<std::mutex> lk(mu_);
  std::vector<Json> out(ring_.begin(), ring_.end());
  ring_.clear();
  return out;
}

void HistoryStore::append(Json event) {
  try {
    std::lock_guard<std::mutex> lk(mu_);
    if (path_.empty() && ring_cap_ == 0) return;
    seq_ += 1;
    event["seq"] = seq_;
    event["ts_ms"] = epoch_millis_now();
    if (ring_cap_ > 0) {
      if (static_cast<int64_t>(ring_.size()) >= ring_cap_) {
        ring_.pop_front();  // oldest-out: the fold wants the recent window
        ring_dropped_ += 1;
      }
      ring_.push_back(event);
    }
    if (path_.empty()) return;
    out_ << event.dump() << "\n";
    // Flush per event: the store exists for postmortems and live replay;
    // a buffered tail lost to a crash defeats both. Event rates are
    // control-plane (per quorum/heal/beat-step), not hot-loop.
    out_.flush();
  } catch (const std::exception&) {
    // never let history IO take down the control plane
  }
}

int64_t HistoryStore::events_written() const {
  std::lock_guard<std::mutex> lk(mu_);
  return seq_;
}

Json history_fold(const Json& events) {
  Json kinds = Json::object();
  std::set<std::string> replicas;
  int64_t count = 0;
  int64_t last_quorum_id = -1;
  int64_t max_step = -1;
  int64_t first_ts = -1;
  int64_t last_ts = -1;

  for (const auto& e : events.as_array()) {
    count += 1;
    std::string kind = e.get_or("kind", Json("unknown")).as_string();
    kinds[kind] =
        kinds.contains(kind) ? kinds.get(kind).as_int() + 1 : int64_t{1};
    if (e.contains("replica_id"))
      replicas.insert(e.get("replica_id").as_string());
    if (e.contains("participants")) {
      for (const auto& rid : e.get("participants").as_array())
        replicas.insert(rid.as_string());
    }
    if (e.contains("quorum_id"))
      last_quorum_id = e.get("quorum_id").as_int();
    if (e.contains("step"))
      max_step = std::max(max_step, e.get("step").as_int());
    if (e.contains("to_step"))
      max_step = std::max(max_step, e.get("to_step").as_int());
    if (e.contains("ts_ms")) {
      int64_t ts = e.get("ts_ms").as_int();
      if (first_ts < 0) first_ts = ts;
      last_ts = ts;
    }
  }

  auto kind_count = [&](const char* k) -> int64_t {
    return kinds.contains(k) ? kinds.get(k).as_int() : 0;
  };

  Json summary = Json::object();
  summary["count"] = count;
  summary["kinds"] = kinds;
  Json rids = Json::array();
  for (const auto& rid : replicas) rids.push_back(rid);
  summary["replicas"] = rids;
  summary["quorum_transitions"] = kind_count("quorum");
  summary["last_quorum_id"] = last_quorum_id;
  summary["heals"] = kind_count("heal");
  summary["ejections"] = kind_count("eject");
  summary["readmissions"] = kind_count("readmit");
  summary["warns"] = kind_count("straggler_warn");
  summary["max_step"] = max_step;
  summary["first_ts_ms"] = first_ts;
  summary["last_ts_ms"] = last_ts;
  return summary;
}

}  // namespace tft
