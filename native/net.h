// POSIX TCP helpers with deadlines for the control-plane wire protocol.
// Equivalent role to the reference's src/net.rs (channel connect with
// keepalive + backoff retry) but for raw sockets.
#pragma once

#include <chrono>
#include <cstdint>
#include <optional>
#include <string>

namespace tft {

using Clock = std::chrono::steady_clock;
using TimePoint = Clock::time_point;
using Millis = std::chrono::milliseconds;

inline TimePoint deadline_from_ms(int64_t ms) { return Clock::now() + Millis(ms); }
int64_t ms_until(TimePoint deadline);

// RAII socket fd.
class Socket {
 public:
  Socket() = default;
  explicit Socket(int fd) : fd_(fd) {}
  Socket(const Socket&) = delete;
  Socket& operator=(const Socket&) = delete;
  Socket(Socket&& o) noexcept : fd_(o.fd_) { o.fd_ = -1; }
  Socket& operator=(Socket&& o) noexcept;
  ~Socket();

  int fd() const { return fd_; }
  bool valid() const { return fd_ >= 0; }
  void close();
  // Wake any thread blocked in recv/send on this socket WITHOUT freeing the
  // fd: safe to call from another thread (close() would race the user and
  // the freed fd number could be reallocated mid-syscall).
  void shutdown_rdwr();

  // All throw std::runtime_error on failure; timeout errors contain "timed out".
  void send_all(const void* data, size_t len, TimePoint deadline);
  void recv_all(void* data, size_t len, TimePoint deadline);
  // Peek up to len bytes without consuming (used for HTTP-vs-frame sniffing).
  size_t peek(void* data, size_t len, TimePoint deadline);

 private:
  int fd_ = -1;
};

// Listener bound to host:port (port 0 -> ephemeral). Accept with timeout.
class Listener {
 public:
  // bind format: "host:port". Throws on failure.
  explicit Listener(const std::string& bind);
  ~Listener();
  Listener(const Listener&) = delete;

  // Local port actually bound.
  int port() const { return port_; }
  // Blocks up to timeout; returns nullopt on timeout, throws on error.
  // Wakes and returns nullopt promptly after shutdown().
  std::optional<Socket> accept(Millis timeout);
  void shutdown();

 private:
  int fd_ = -1;
  int port_ = 0;
};

// Connect with deadline; retries with backoff until deadline (reference
// behavior: src/net.rs:16-42 connect retry loop).
Socket connect_with_retry(const std::string& host, int port, TimePoint deadline);

// Parse "host:port" (supports "[v6]:port").
std::pair<std::string, int> split_host_port(const std::string& addr);

std::string local_hostname();

}  // namespace tft
