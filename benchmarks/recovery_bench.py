"""Recovery wall-clock benchmark (the BASELINE.md north-star metric the
reference never publishes: time to recover after a replica kill).

Two replica groups train a synthetic model through a real lighthouse +
managers; at a configured step one replica dies. Runs on either data plane:

- ``--plane host``: ProcessGroupHost (pickle/raw frames over TCP) — the
  Gloo-role plane. Failure detection is socket-close driven (fast).
- ``--plane device``: ProcessGroupXLA local mode — collectives are XLA
  reductions over a device mesh (virtual CPU devices stand in for chips,
  exactly like the driver's dryrun). Failure detection is timeout→abort
  driven, the same semantics as the reference's NCCL plane
  (torchft/process_group.py:780-891): a dead peer's contribution never
  arrives, the armed deadline aborts the op, the step is discarded.

Measured, in seconds (every component separately — VERDICT round-3 item 4):

- **steady_step_s**: survivor's median inter-commit gap before the kill.
- **detection_quorum_s**: kill -> survivor's first quorum with a bumped
  quorum_id (includes the discarded-step timeout on the device plane,
  heartbeat expiry, and the quorum RPC).
- **pg_configure_s**: the survivor's timed ``pg.configure`` call for that
  quorum (communicator rebuild only).
- **heal_recv_s**: the restarted replica's ``recv_checkpoint`` wall-clock
  (checkpoint transfer only).
- **recovery_s**: kill -> survivor's first committed step past the kill
  step (the end-to-end number).
- **rejoin_s**: restarted replica's Manager construction -> first commit.
- **reconfigure_s**: legacy alias of ``recovery_s`` kept so round<=3
  artifacts stay comparable — NOT the communicator rebuild, which is
  ``pg_configure_s``.
- **quorum_overlap_s / configure_prepare_s / configure_commit_s**: the
  survivor Manager's prepare/commit split timings — how much of the
  membership change ran on the quorum thread (overlapped with the train
  step) vs. the serialized commit at the next safe point.
- **heal_chunks / heal_mb_per_s**: chunk count and wire throughput of the
  rejoiner's streamed heal transfer (pipelined transports).

    python benchmarks/recovery_bench.py [--plane device] [--size-mb 256]

Prints one JSON line with all components.
"""

import argparse
import json
import os
import sys
import threading
import time
from concurrent.futures import ThreadPoolExecutor

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))

import numpy as np  # noqa: E402


class _Die(Exception):
    pass


def _timed_configure(pg, log: list):
    """Shadow pg.prepare_configure with a wall-clock-recording wrapper.

    The Manager reconfigures through ``prepare_configure`` since the
    prepare/commit split; the base implementation routes through
    ``self.configure``, and split PGs (ProcessGroupXLA) override it, so
    shadowing prepare catches every reconfigure on both planes. This
    times the PREPARE (control-plane) half; the commit half is reported
    separately via ``manager.timings()['configure_commit_s']``."""
    inner = pg.prepare_configure

    def prepare_configure(*a, **k):
        t0 = time.perf_counter()
        out = inner(*a, **k)
        log.append((time.perf_counter() - t0, time.perf_counter()))
        return out

    pg.prepare_configure = prepare_configure
    return pg


def run(
    size_mb: int,
    steps: int,
    kill_at: int,
    plane: str = "host",
    collective_timeout: float = 5.0,
    transport: str = "http",
) -> dict:
    """``transport``: "http" (default), "pg" (heal over a dedicated
    recovery ProcessGroupHost via PGTransport), or "pg-inplace" /
    "http-inplace" (the Manager-derived template so received leaves land
    in place)."""
    from torchft_tpu.checkpointing import HTTPTransport, PGTransport
    from torchft_tpu.coordination import LighthouseServer
    from torchft_tpu.manager import Manager
    from torchft_tpu.process_group import ProcessGroupHost as _RecoveryPG

    if transport not in ("http", "http-inplace", "pg", "pg-inplace"):
        # argparse guards only the CLI; programmatic callers (bench.py's
        # child scripts) must not get a silently mislabeled record
        raise ValueError(f"unknown transport {transport!r}: "
                         "expected http | http-inplace | pg | pg-inplace")

    if plane == "device":
        import jax

        if len(jax.devices()) < 2:
            raise RuntimeError(
                "device plane needs >=2 devices; call "
                "force_virtual_cpu_devices(2) before jax init"
            )

    lh = LighthouseServer(
        bind="127.0.0.1:0", min_replicas=1, join_timeout_ms=2000,
        quorum_tick_ms=20, heartbeat_timeout_ms=1000,
    )
    n_elem = size_mb * (1 << 20) // 4
    commit_times: dict = {0: [], 1: []}
    rejoin_s = [None]
    heal_recv_s = [None]
    heal_stream = [None]
    detection_quorum_s = [None]
    survivor_configures: list = []
    survivor_timings = [None]
    kill_time = [None]
    kill_step = [None]

    def make_pg(timeout: float):
        if plane == "device":
            from torchft_tpu.process_group_xla import ProcessGroupXLA

            return ProcessGroupXLA(timeout=timeout, mode="local")
        from torchft_tpu.process_group import ProcessGroupHost

        return ProcessGroupHost(timeout=timeout)

    def make_grad():
        if plane == "device":
            import jax.numpy as jnp

            return {"w": jnp.full((n_elem,), 0.01, jnp.float32)}
        return {"w": np.full(n_elem, 0.01, dtype=np.float32)}

    def replica(rid: int, start_step_barrier: threading.Barrier) -> None:
        attempts = 0
        while attempts < 2:
            attempts += 1
            state = {"params": {"w": np.zeros(n_elem, dtype=np.float32)}}
            t_ctor = time.perf_counter()
            manager = None
            healed = [False]

            recovery_pg = None
            template_fn = None
            if transport.endswith("-inplace"):
                # the Manager's own live composite (late-bound: `manager`
                # is assigned below) — leaf alignment with the sender by
                # construction
                def template_fn():
                    return manager.state_dict_template()

            if transport.startswith("pg"):
                recovery_pg = _RecoveryPG(timeout=30.0)
                tx = PGTransport(recovery_pg, timeout=30.0,
                                 state_dict_template=template_fn)
            else:
                tx = HTTPTransport(timeout=30.0,
                                   state_dict_template=template_fn)
            if attempts == 2:
                # the rejoiner's heal transfer, isolated from quorum time.
                # Both receive entry points are wrapped: multi-source
                # transports (HTTP) are healed through
                # recv_checkpoint_multi, single-source ones (PG) through
                # recv_checkpoint.
                def _timed(inner):
                    def wrapped(*a, **k):
                        t0 = time.perf_counter()
                        out = inner(*a, **k)
                        heal_recv_s[0] = time.perf_counter() - t0
                        heal_stream[0] = tx.last_recv_timings()
                        return out

                    return wrapped

                tx.recv_checkpoint = _timed(tx.recv_checkpoint)
                if hasattr(tx, "recv_checkpoint_multi"):
                    tx.recv_checkpoint_multi = _timed(tx.recv_checkpoint_multi)

            pg = make_pg(collective_timeout)
            if rid == 0:
                _timed_configure(pg, survivor_configures)
            try:
                manager = Manager(
                    pg=pg,
                    load_state_dict=lambda sd: state.update(
                        params={k: np.asarray(v) for k, v in sd["params"].items()}
                    ),
                    state_dict=lambda: {"params": dict(state["params"])},
                    min_replica_size=1,
                    # async on BOTH planes since the prepare/commit
                    # configure split: the device plane's control-plane
                    # round-trip now overlaps the train step too
                    use_async_quorum=True,
                    replica_id=f"recovery_bench_{rid}",
                    lighthouse_addr=f"127.0.0.1:{lh.port}",
                    timeout=collective_timeout,
                    quorum_timeout=15.0,
                    checkpoint_transport=tx,
                )
                if attempts == 1:
                    start_step_barrier.wait(timeout=60)
                last_qid = [manager.current_quorum_id()]
                while manager.current_step() < steps:
                    manager.start_quorum()
                    if (
                        rid == 0
                        and kill_time[0] is not None
                        and detection_quorum_s[0] is None
                        and manager.current_quorum_id() != last_qid[0]
                    ):
                        detection_quorum_s[0] = (
                            time.perf_counter() - kill_time[0]
                        )
                        # the reconfigure cycle just fully joined (the id
                        # bump is only visible after it) — snapshot its
                        # per-phase timings before steady-state quorums
                        # overwrite quorum_overlap_s
                        survivor_timings[0] = manager.timings()
                    last_qid[0] = manager.current_quorum_id()
                    avg = manager.allreduce(make_grad()).get_future().wait(60)
                    if manager.should_commit():
                        state["params"]["w"] = state["params"]["w"] - np.asarray(
                            avg["w"]
                        )
                        now = time.perf_counter()
                        commit_times[rid].append((manager.current_step(), now))
                        if attempts == 2 and not healed[0]:
                            rejoin_s[0] = now - t_ctor
                            healed[0] = True
                    if (
                        attempts == 1
                        and rid == 1
                        and manager.current_step() >= kill_at
                    ):
                        kill_time[0] = time.perf_counter()
                        kill_step[0] = manager.current_step()
                        raise _Die()
                if rid == 0:
                    # detection-time snapshot wins for overlap (it caught
                    # the reconfigure cycle); late-arriving keys (the
                    # commit applied at a later sync point) fill from the
                    # final state
                    snap = survivor_timings[0] or {}
                    survivor_timings[0] = {**manager.timings(), **snap}
                return
            except _Die:
                # Crash-faithful teardown: shutdown(wait=False) stops the
                # heartbeat loop and closes sockets — the same observable
                # effects as process death (there is no graceful-leave RPC in
                # the protocol), so the lighthouse still detects the failure
                # via heartbeat expiry and the survivor's gap includes that
                # detection latency.
                manager.shutdown(wait=False)
                continue
            finally:
                # manager stays None if the constructor raised — don't let a
                # NameError here mask the original failure.
                if manager is not None and manager.current_step() >= steps:
                    manager.shutdown(wait=False)
                if recovery_pg is not None:
                    recovery_pg.shutdown()  # caller-owned (pg transports)

    barrier = threading.Barrier(2)
    with ThreadPoolExecutor(max_workers=2) as ex:
        futs = [ex.submit(replica, r, barrier) for r in range(2)]
        for f in futs:
            f.result(timeout=600)
    lh.shutdown()

    # The recovery metric is kill -> survivor's first commit of a LATER
    # protocol step (detect -> new quorum -> rebuilt communicator -> step).
    # Anchoring on the step number, not wall-clock adjacency, keeps the
    # survivor's concurrent same-step commit and the later heal-serving
    # stall from masquerading as (or hiding) the detection latency.
    times0 = [t for _s, t in commit_times[0]]
    gaps = np.diff(times0)
    assert len(gaps) > 3, "not enough survivor commits"
    assert kill_time[0] is not None, "kill never happened"
    after = [t for s, t in commit_times[0] if s > kill_step[0]]
    assert after, "survivor never committed after the kill"
    recovery = float(min(after) - kill_time[0])
    steady = float(np.median(gaps))
    # the survivor's communicator rebuild for the post-kill quorum: the
    # first configure that happened after the kill
    reconf = next(
        (d for d, at in survivor_configures if at > kill_time[0]), None
    )
    timings = survivor_timings[0] or {}
    stream = heal_stream[0]
    return {
        "plane": plane,
        "transport": transport,
        "reconfigure_s": round(recovery, 3),  # legacy name (round<=3): e2e
        "recovery_s": round(recovery, 3),
        "detection_quorum_s": (
            round(detection_quorum_s[0], 3) if detection_quorum_s[0] else None
        ),
        "pg_configure_s": round(reconf, 4) if reconf is not None else None,
        # prepare/commit split metrics (survivor's Manager): overlap is the
        # control-plane wall-clock hidden from the train step on the quorum
        # thread; commit is the only serialized remainder
        "quorum_overlap_s": (
            round(timings["quorum_overlap_s"], 4)
            if "quorum_overlap_s" in timings else None
        ),
        "configure_prepare_s": (
            round(timings["configure_prepare_s"], 4)
            if "configure_prepare_s" in timings else None
        ),
        "configure_commit_s": (
            round(timings["configure_commit_s"], 4)
            if "configure_commit_s" in timings else None
        ),
        "heal_recv_s": (
            round(heal_recv_s[0], 3) if heal_recv_s[0] is not None else None
        ),
        # chunk-stream stats of the rejoiner's heal (pipelined transports)
        "heal_chunks": stream.num_chunks if stream is not None else None,
        "heal_mb_per_s": (
            round(stream.mb_per_s, 2) if stream is not None else None
        ),
        "rejoin_s": round(rejoin_s[0], 3) if rejoin_s[0] else None,
        "steady_step_s": round(steady, 4),
        "collective_timeout_s": collective_timeout,
        "size_mb": size_mb,
    }


def main() -> None:
    p = argparse.ArgumentParser()
    p.add_argument("--size-mb", type=int, default=64)
    p.add_argument("--steps", type=int, default=30)
    p.add_argument("--kill-at", type=int, default=10)
    p.add_argument("--plane", choices=["host", "device"], default="host")
    p.add_argument("--transport",
                   choices=["http", "http-inplace", "pg", "pg-inplace"],
                   default="http")
    p.add_argument("--collective-timeout", type=float, default=5.0)
    args = p.parse_args()
    if args.plane == "device":
        from torchft_tpu.utils import force_virtual_cpu_devices

        force_virtual_cpu_devices(2)
    print(json.dumps(run(args.size_mb, args.steps, args.kill_at,
                         plane=args.plane, transport=args.transport,
                         collective_timeout=args.collective_timeout)))


if __name__ == "__main__":
    main()
