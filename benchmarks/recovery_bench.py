"""Recovery wall-clock benchmark (the BASELINE.md north-star metric the
reference never publishes: time to recover after a replica kill).

Two replica groups train a synthetic model through a real lighthouse +
managers; at a configured step one replica dies. Measured, in seconds:

- **reconfigure**: kill -> survivor's first committed step with a step
  number past the kill step (detect dead peer -> abort -> new quorum ->
  rebuilt communicator -> step).
- **rejoin**: wall-clock from the restarted replica constructing its Manager
  to its first committed step (quorum join + live checkpoint heal + commit).

    python benchmarks/recovery_bench.py [--size-mb 64] [--steps 30] [--kill-at 10]

Prints one JSON line: {"reconfigure_s", "rejoin_s", "steady_step_s", "size_mb"}.
"""

import argparse
import json
import os
import sys
import threading
import time
from concurrent.futures import ThreadPoolExecutor

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))

import numpy as np  # noqa: E402

from torchft_tpu.coordination import LighthouseServer  # noqa: E402
from torchft_tpu.manager import Manager  # noqa: E402
from torchft_tpu.process_group import ProcessGroupHost  # noqa: E402


class _Die(Exception):
    pass


def run(size_mb: int, steps: int, kill_at: int) -> dict:
    lh = LighthouseServer(
        bind="127.0.0.1:0", min_replicas=1, join_timeout_ms=2000,
        quorum_tick_ms=20, heartbeat_timeout_ms=1000,
    )
    n_elem = size_mb * (1 << 20) // 4
    commit_times: dict = {0: [], 1: []}
    rejoin_s = [None]
    kill_time = [None]
    kill_step = [None]

    def replica(rid: int, start_step_barrier: threading.Barrier) -> None:
        attempts = 0
        while attempts < 2:
            attempts += 1
            state = {"params": {"w": np.zeros(n_elem, dtype=np.float32)}}
            t_ctor = time.perf_counter()
            manager = None
            healed = [False]
            try:
                manager = Manager(
                    pg=ProcessGroupHost(timeout=5.0),
                    load_state_dict=lambda sd: state.update(
                        params={k: np.asarray(v) for k, v in sd["params"].items()}
                    ),
                    state_dict=lambda: {"params": dict(state["params"])},
                    min_replica_size=1,
                    use_async_quorum=True,
                    replica_id=f"recovery_bench_{rid}",
                    lighthouse_addr=f"127.0.0.1:{lh.port}",
                    timeout=5.0,
                    quorum_timeout=10.0,
                )
                if attempts == 1:
                    start_step_barrier.wait(timeout=30)
                while manager.current_step() < steps:
                    manager.start_quorum()
                    grad = {"w": np.full(n_elem, 0.01, dtype=np.float32)}
                    avg = manager.allreduce(grad).get_future().wait(30)
                    if manager.should_commit():
                        state["params"]["w"] = state["params"]["w"] - avg["w"]
                        now = time.perf_counter()
                        commit_times[rid].append((manager.current_step(), now))
                        if attempts == 2 and not healed[0]:
                            rejoin_s[0] = now - t_ctor
                            healed[0] = True
                    if (
                        attempts == 1
                        and rid == 1
                        and manager.current_step() >= kill_at
                    ):
                        kill_time[0] = time.perf_counter()
                        kill_step[0] = manager.current_step()
                        raise _Die()
                return
            except _Die:
                # Crash-faithful teardown: shutdown(wait=False) stops the
                # heartbeat loop and closes sockets — the same observable
                # effects as process death (there is no graceful-leave RPC in
                # the protocol), so the lighthouse still detects the failure
                # via heartbeat expiry and the survivor's gap includes that
                # detection latency.
                manager.shutdown(wait=False)
                continue
            finally:
                # manager stays None if the constructor raised — don't let a
                # NameError here mask the original failure.
                if manager is not None and manager.current_step() >= steps:
                    manager.shutdown(wait=False)

    barrier = threading.Barrier(2)
    with ThreadPoolExecutor(max_workers=2) as ex:
        futs = [ex.submit(replica, r, barrier) for r in range(2)]
        for f in futs:
            f.result(timeout=300)
    lh.shutdown()

    # The reconfigure metric is kill -> survivor's first commit of a LATER
    # protocol step (detect -> new quorum -> rebuilt communicator -> step).
    # Anchoring on the step number, not wall-clock adjacency, keeps the
    # survivor's concurrent same-step commit and the later heal-serving
    # stall from masquerading as (or hiding) the detection latency.
    times0 = [t for _s, t in commit_times[0]]
    gaps = np.diff(times0)
    assert len(gaps) > 3, "not enough survivor commits"
    assert kill_time[0] is not None, "kill never happened"
    after = [t for s, t in commit_times[0] if s > kill_step[0]]
    assert after, "survivor never committed after the kill"
    reconfigure = float(min(after) - kill_time[0])
    steady = float(np.median(gaps))
    return {
        "reconfigure_s": round(reconfigure, 3),
        "rejoin_s": round(rejoin_s[0], 3) if rejoin_s[0] else None,
        "steady_step_s": round(steady, 4),
        "size_mb": size_mb,
    }


def main() -> None:
    p = argparse.ArgumentParser()
    p.add_argument("--size-mb", type=int, default=64)
    p.add_argument("--steps", type=int, default=30)
    p.add_argument("--kill-at", type=int, default=10)
    args = p.parse_args()
    print(json.dumps(run(args.size_mb, args.steps, args.kill_at)))


if __name__ == "__main__":
    main()
