"""Serving-plane load harness: loopback workers under live traffic.

Stands up a snapshot registry, P publishers (the "training fleet": a
driver thread commits a new version every publish interval, all live
publishers publish the SAME committed params — the lockstep the quorum
protocol guarantees), and a grid of worker counts answering real HTTP
``/infer`` traffic on loopback:

    python benchmarks/serving_bench.py           # full grid + BENCH_SERVE.json
    python benchmarks/serving_bench.py --smoke   # tier-1 gate: 1 point

Phases per worker count: warm (every worker reaches the first version),
load (closed-loop request threads, latency histogram + lag sampling).
At the largest worker count the load phase takes a CHAOS turn: mid-
traffic, publisher 0 is killed outright and its health flips to ``warn``
— the registry must drain it, workers must fail over their pulls, and
(the headline gate) **zero requests may fail**; a quorum "reconfigure"
(quorum_id bump) also lands mid-load to prove version monotonicity under
traffic.  The run ends by checking every worker's final parameters are
bitwise-equal to the surviving fleet's published snapshot.

Numbers are loopback-on-shared-vCPUs: requests/s measures the plane's
bookkeeping cost, not network serving capacity — see the provenance
block in BENCH_SERVE.json.
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import threading
import time
import urllib.request

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from torchft_tpu.serving import (  # noqa: E402
    ServeConfig,
    ServeWorker,
    SnapshotPublisher,
    SnapshotRegistry,
)

REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


class _Fleet:
    """Driver for P lockstep publishers + a mutable health view."""

    def __init__(self, cfg: ServeConfig, n_publishers: int, n_params: int,
                 publish_interval_s: float) -> None:
        self.cfg = cfg
        self.health = {"replicas": {}}  # mutated by the chaos turn
        self._health_lock = threading.Lock()
        self.registry = SnapshotRegistry(
            health_fn=self._health_view, drain_on=cfg.drain_on, poll_s=0.05
        )
        cfg.registry = self.registry.url
        self.publishers = []
        for i in range(n_publishers):
            rid = f"serve_replica_{i}"
            self.publishers.append(
                SnapshotPublisher(rid, config=cfg, registry_url=self.registry.url)
            )
            with self._health_lock:
                self.health["replicas"][rid] = {"state": "ok"}
        self.rng = np.random.RandomState(1234)
        self.params = {"w": self.rng.randn(n_params).astype(np.float32)}
        self.quorum_id = 1
        self.step = 0
        self.dead: set = set()
        self.publish_interval_s = publish_interval_s
        self._stop = threading.Event()
        self._thread = threading.Thread(target=self._run, daemon=True)

    def _health_view(self) -> dict:
        with self._health_lock:
            return json.loads(json.dumps(self.health))

    def set_state(self, i: int, state: str) -> None:
        with self._health_lock:
            self.health["replicas"][f"serve_replica_{i}"] = {"state": state}

    def start(self) -> None:
        self.commit_once()  # version 0 exists before any worker starts
        self._thread.start()

    def commit_once(self) -> None:
        # one committed training step: identical params reach every live
        # replica's publisher (what the commit path guarantees)
        self.params["w"] = (
            self.params["w"]
            + self.rng.randn(self.params["w"].size).astype(np.float32) * 0.01
        )
        for i, pub in enumerate(self.publishers):
            if i not in self.dead:
                pub.publish(self.quorum_id, self.step, self.params)
        self.step += 1

    def kill(self, i: int) -> None:
        """Abrupt publisher death + the health ledger noticing (warn)."""
        self.dead.add(i)
        self.publishers[i].kill()
        self.set_state(i, "warn")

    def reconfigure(self) -> None:
        self.quorum_id += 1

    def _run(self) -> None:
        while not self._stop.wait(self.publish_interval_s):
            self.commit_once()

    def stop(self) -> None:
        self._stop.set()
        self._thread.join(timeout=5)

    def shutdown(self) -> None:
        self.stop()
        for i, pub in enumerate(self.publishers):
            if i not in self.dead:
                pub.shutdown()
        self.registry.shutdown()

    def survivor_flat(self) -> np.ndarray:
        for i, pub in enumerate(self.publishers):
            if i not in self.dead:
                flat = pub.ref_flat()
                if flat is not None:
                    return flat
        raise RuntimeError("no surviving publisher")

    def latest_version(self):
        best = None
        for i, pub in enumerate(self.publishers):
            if i not in self.dead and pub.version is not None:
                if best is None or pub.version > best:
                    best = pub.version
        return best


class _LoadGen:
    """Closed-loop HTTP request threads against a set of workers."""

    def __init__(self, worker_urls, n_threads: int, timeout_s: float = 5.0):
        self.urls = list(worker_urls)
        self.n_threads = n_threads
        self.timeout_s = timeout_s
        self.latencies_ms = []
        self.failures = []
        self._lock = threading.Lock()
        self._stop = threading.Event()
        self._threads = []

    def _run(self, tid: int) -> None:
        i = 0
        while not self._stop.is_set():
            url = self.urls[(tid + i) % len(self.urls)]
            seed = tid * 1_000_003 + i
            t0 = time.perf_counter()
            try:
                with urllib.request.urlopen(
                    f"{url}/infer?seed={seed}", timeout=self.timeout_s
                ) as r:
                    body = json.loads(r.read().decode())
                    ok = r.status == 200 and body.get("result") is not None
                err = None if ok else f"bad body: {body}"
            except Exception as e:  # noqa: BLE001 — a failure IS the metric
                err = repr(e)
            dt_ms = (time.perf_counter() - t0) * 1e3
            with self._lock:
                if err is None:
                    self.latencies_ms.append(dt_ms)
                else:
                    self.failures.append(err)
            i += 1

    def start(self) -> None:
        for t in range(self.n_threads):
            th = threading.Thread(target=self._run, args=(t,), daemon=True)
            th.start()
            self._threads.append(th)

    def stop(self) -> None:
        self._stop.set()
        for th in self._threads:
            th.join(timeout=self.timeout_s + 1)


def _percentile(xs, q):
    return float(np.percentile(np.asarray(xs), q)) if xs else 0.0


def run_point(fleet: _Fleet, n_workers: int, load_s: float, chaos: bool,
              cfg: ServeConfig) -> dict:
    workers = [
        ServeWorker(fleet.registry.url, config=cfg, name=f"w{n_workers}_{i}")
        for i in range(n_workers)
    ]
    try:
        warm_deadline = time.monotonic() + 30.0
        for w in workers:
            if not w.wait_version((fleet.quorum_id, 0), timeout=max(
                0.1, warm_deadline - time.monotonic()
            )):
                raise RuntimeError(f"worker {w.name} never warmed")

        gen = _LoadGen([w.url for w in workers], n_threads=max(2, n_workers))
        lags = []
        gen.start()
        t0 = time.monotonic()
        killed = reconfigured = False
        while time.monotonic() - t0 < load_s:
            time.sleep(0.05)
            for w in workers:
                lags.append(w.status()["lag_steps"])
            elapsed = time.monotonic() - t0
            if chaos and not reconfigured and elapsed > load_s * 0.25:
                fleet.reconfigure()  # quorum change mid-traffic
                reconfigured = True
            if chaos and not killed and elapsed > load_s * 0.5:
                fleet.kill(0)  # replica death mid-traffic
                killed = True
        gen.stop()
        wall_s = time.monotonic() - t0

        # quiesce: stop publishing, let every worker converge to the tip
        fleet.stop()
        final_version = fleet.latest_version()
        converged = all(
            w.wait_version(final_version, timeout=20.0) for w in workers
        )
        survivor = fleet.survivor_flat()
        bitwise = converged and all(
            np.array_equal(w.params_flat(), survivor) for w in workers
        )

        counters = {k: 0 for k in workers[0].counters}
        for w in workers:
            for k, v in w.counters.items():
                counters[k] += v
        n_ok = len(gen.latencies_ms)
        return {
            "workers": n_workers,
            "chaos": chaos,
            "requests_ok": n_ok,
            "requests_failed": len(gen.failures),
            "failure_samples": gen.failures[:5],
            "rps": n_ok / wall_s if wall_s > 0 else 0.0,
            "p50_ms": _percentile(gen.latencies_ms, 50),
            "p99_ms": _percentile(gen.latencies_ms, 99),
            "lag_p50_steps": _percentile(lags, 50),
            "lag_p99_steps": _percentile(lags, 99),
            "converged": bool(converged),
            "bitwise_equal": bool(bitwise),
            "final_version": list(final_version) if final_version else None,
            "counters": counters,
        }
    finally:
        for w in workers:
            w.shutdown()


def run(smoke: bool) -> dict:
    n_params = 65_536 if smoke else 524_288
    worker_grid = [2] if smoke else [1, 2, 4]
    load_s = 3.0 if smoke else 8.0
    cfg = ServeConfig(
        registry="", max_lag=8, compress="fp8",
        poll_s=0.02, drain_on="warn", timeout_s=15.0,
    )
    points = []
    delta_per_version = full_per_pull = 0.0
    for idx, n_workers in enumerate(worker_grid):
        chaos = idx == len(worker_grid) - 1  # chaos turn at the largest point
        fleet = _Fleet(
            cfg, n_publishers=2 if smoke else 3, n_params=n_params,
            publish_interval_s=0.10 if smoke else 0.08,
        )
        try:
            fleet.start()
            point = run_point(fleet, n_workers, load_s, chaos, cfg)
            points.append(point)
            c = point["counters"]
            if c["delta_pulls_total"]:
                delta_per_version = c["delta_bytes_total"] / c["delta_pulls_total"]
            if c["full_pulls_total"]:
                full_per_pull = c["full_bytes_total"] / c["full_pulls_total"]
        finally:
            fleet.shutdown()

    chaos_point = points[-1]
    savings = (full_per_pull / delta_per_version) if delta_per_version else 0.0
    metrics = {
        "serving_points": points,
        "serving_rps_by_workers": {
            str(p["workers"]): round(p["rps"], 1) for p in points
        },
        "serving_p50_ms": chaos_point["p50_ms"],
        "serving_p99_ms": chaos_point["p99_ms"],
        "serving_lag_p50_steps": chaos_point["lag_p50_steps"],
        "serving_lag_p99_steps": chaos_point["lag_p99_steps"],
        "serving_failed_requests": sum(p["requests_failed"] for p in points),
        "serving_requests_ok": sum(p["requests_ok"] for p in points),
        "serving_converged": all(p["converged"] for p in points),
        "serving_bitwise_equal": all(p["bitwise_equal"] for p in points),
        "serving_delta_bytes_per_version": round(delta_per_version, 1),
        "serving_full_bytes_per_pull": round(full_per_pull, 1),
        "serving_delta_savings_x": round(savings, 2),
        "serving_n_params": n_params,
        "serving_compress": cfg.compress,
    }
    return metrics


def main() -> None:
    parser = argparse.ArgumentParser(description=__doc__)
    parser.add_argument("--smoke", action="store_true")
    args = parser.parse_args()

    metrics = run(smoke=args.smoke)

    if not args.smoke:
        artifact = {
            "provenance": {
                "harness": "benchmarks/serving_bench.py (loopback)",
                "caveats": [
                    "loopback HTTP on shared vCPUs: rps/latency measure the "
                    "serving plane's bookkeeping cost, not network capacity",
                    "publishers are driven in lockstep by one thread (the "
                    "commit-path guarantee), not by live training",
                    "requests/s is closed-loop with 2x-workers client "
                    "threads; p99 includes client-side connection setup",
                ],
                "host": os.uname().nodename,
                "cpu_count": os.cpu_count(),
            },
            "metrics": {
                k: v for k, v in metrics.items() if k != "serving_points"
            },
            "points": metrics["serving_points"],
        }
        out = os.path.join(REPO_ROOT, "BENCH_SERVE.json")
        with open(out, "w") as f:
            json.dump(artifact, f, indent=2, sort_keys=True)
            f.write("\n")
        print(f"wrote {out}", file=sys.stderr)

    print(json.dumps(metrics))


if __name__ == "__main__":
    main()
