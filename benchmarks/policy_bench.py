"""Adaptive policy plane cost + replay throughput bench.

Two promises from docs/operations.md "Adaptive policies" are measured
instead of asserted:

- **The engine is ~free for the fleet.** The lighthouse folds its event
  ring into signals once per ``TORCHFT_POLICY_INTERVAL_S`` (default 5 s),
  so the honest per-step accounting is the fold's duty cycle: one
  fold+evaluate over a 1000-replica window, amortized over the interval.
  ``policy_fold_duty_cycle_pct`` must stay under 0.5% — equivalently, the
  amortized fold cost per managed step is <0.5% of that step.
- **Offline replay is fast enough to iterate on.** ``python -m
  torchft_tpu.policy replay`` re-folds committed history through the SAME
  ``fold_signals`` the live engine uses; ``replay_events_per_s`` is the
  scoring throughput over the committed 1000-replica fixture
  (``benchmarks/fixtures/policy_history_1000replicas.jsonl.gz``).

It also runs a short LIVE managed loop (the ft_overhead trainer) under
``TORCHFT_POLICY=observe`` with the engine attached, proving frames reach
the manager's quorum safe point end to end (``policy_intents`` > 0 in
``Manager.timings()``) while measuring the managed step the duty cycle is
quoted against.

The fixture is deterministic (no wall clock, no RNG — a fixed phase
script over 1000 replicas: calm, a churn storm with link-fault growth,
recovery) and committed; ``--regen`` rewrites it byte-identically.

    python benchmarks/policy_bench.py [--smoke] [--regen]

Prints one JSON line; ``bench.py --policy`` merges the row into
BENCH_POLICY.json and ``bench.py --policy --smoke`` is the fast-tier CI
gate (tests/test_bench_smoke.py).
"""

import gzip
import json
import os
import statistics
import sys
import time

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))
sys.path.insert(0, os.path.join(os.path.dirname(__file__), "..", "examples"))

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
FIXTURE = os.path.join(
    REPO, "benchmarks", "fixtures", "policy_history_1000replicas.jsonl.gz"
)

N_REPLICAS = 1000
SPAN_S = 600  # calm 0-200, churn storm 200-400, recovery 400-600
QUORUM_EVERY_S = 10
TELEMETRY_EVERY_S = 5
TELEMETRY_REPORTERS = 50  # replicas that emit telemetry snapshots


def _median(xs):
    return statistics.median(xs) if xs else 0.0


def generate_fixture() -> list:
    """The committed 1000-replica narrative, fully deterministic."""
    replicas = [f"replica_{i:04d}" for i in range(N_REPLICAS)]
    events = []
    seq = 0

    def emit(ts_s, kind, **fields):
        nonlocal seq
        seq += 1
        events.append({"ts_ms": ts_s * 1000, "seq": seq, "kind": kind, **fields})

    counters = {r: 0.0 for r in replicas[:TELEMETRY_REPORTERS]}
    for t in range(0, SPAN_S + 1, TELEMETRY_EVERY_S):
        storm = 200 <= t < 400
        if t % QUORUM_EVERY_S == 0:
            if storm:
                # a rotating squall of 20 replicas out per quorum
                out = {(t // QUORUM_EVERY_S * 7 + j) % N_REPLICAS
                       for j in range(20)}
            elif t % 60 == 0 and t > 0:
                out = {(t // 60) % N_REPLICAS}  # background attrition
            else:
                out = set()
            emit(t, "quorum", quorum_id=t // QUORUM_EVERY_S,
                 participants=[r for i, r in enumerate(replicas)
                               if i not in out])
        if storm and t % 20 == 0:
            victim = replicas[(t * 13) % N_REPLICAS]
            emit(t, "eject", replica_id=victim, score=9.5)
            emit(t + 15, "readmit", replica_id=victim)
        if storm and t % 40 == 0:
            emit(t, "straggler_warn",
                 replica_id=replicas[(t * 31) % N_REPLICAS], score=4.2)
        for i, rid in enumerate(sorted(counters)):
            # cumulative link-fault counters: flat when calm, growing
            # through the storm (the link_quality signal differences these)
            if storm:
                counters[rid] += 0.4 + (i % 3) * 0.2
            emit(t, "telemetry", replica_id=rid, telemetry={
                "step": t, "step_s": 0.1,
                "rpc_retries": round(counters[rid], 1),
                "collective_reroute": round(counters[rid] / 2.0, 1),
                "chunk_crc_failures": 0,
            })
    return events


def write_fixture() -> None:
    os.makedirs(os.path.dirname(FIXTURE), exist_ok=True)
    payload = "\n".join(
        json.dumps(e, sort_keys=True) for e in generate_fixture()
    )
    # mtime=0 keeps the gzip byte-identical across regenerations
    with open(FIXTURE, "wb") as f:
        with gzip.GzipFile(fileobj=f, mode="wb", mtime=0) as gz:
            gz.write(payload.encode())


def candidate_spec() -> dict:
    """A second, more aggressive candidate so the replay ranking has a
    real contest (the builtin is the conservative one)."""
    return {
        "name": "aggressive",
        "rules": [
            {"name": "any-churn-lengthen", "signal": "churn_per_min",
             "op": ">", "threshold": 1.0, "release": 0.2,
             "actions": {"TORCHFT_SYNC_EVERY": "128"}},
            {"name": "links-compress-hard", "signal": "link_quality",
             "op": "<", "threshold": 0.99, "release": 0.999,
             "actions": {"TORCHFT_COMPRESS": "int8"}},
        ],
        "clamps": {"TORCHFT_SYNC_EVERY": [1, 512]},
    }


def run(smoke: bool = False) -> dict:
    from torchft_tpu.policy import (
        PolicyEngine,
        PolicySpec,
        builtin_spec,
        rank_policies,
    )
    from torchft_tpu.tracing import load_history

    if not os.path.exists(FIXTURE):
        write_fixture()
    events = load_history(FIXTURE)
    n_events = len(events)

    # -- offline replay throughput (the shared fold code path) -------------
    specs = [builtin_spec(), PolicySpec.from_json(candidate_spec())]
    t0 = time.perf_counter()
    ranking = rank_policies(events, specs, interval_s=5.0, window_s=300.0)
    replay_s = time.perf_counter() - t0
    replay_events_per_s = n_events * len(specs) / replay_s if replay_s else 0.0

    # -- one live-shaped fold+evaluate over the full 1000-replica window ---
    reps = 5 if smoke else 20
    fold_times = []
    for _ in range(reps):
        engine = PolicyEngine(builtin_spec(), mode="observe", window_s=300.0)
        engine.feed(list(events))
        t0 = time.perf_counter()
        engine.evaluate()
        fold_times.append(time.perf_counter() - t0)
    # min, not median: the fold is deterministic code over fixed input, so
    # the fastest rep is the true cost and everything above it is the
    # 1-vCPU host's scheduler (the gate must not flake on neighbor load)
    fold_eval_ms = min(fold_times) * 1000.0

    # -- live managed loop under observe: end-to-end frames + step cost ----
    import optax  # noqa: F401 — fail here, not mid-loop, if absent

    from train_ddp import build_trainer

    from torchft_tpu.coordination import LighthouseServer
    from torchft_tpu.manager import Manager
    from torchft_tpu.process_group import ProcessGroupHost

    steps = 12 if smoke else 40
    interval_s = 0.2  # fast cadence so a short bench still sees frames
    os.environ["TORCHFT_POLICY"] = "observe"
    os.environ["TORCHFT_POLICY_INTERVAL_S"] = str(interval_s)
    state, grad_fn, optimizer, make_batch = build_trainer(0, batch_size=8)
    lh = LighthouseServer(
        bind="127.0.0.1:0", min_replicas=1, join_timeout_ms=200,
        quorum_tick_ms=20, heartbeat_timeout_ms=2000, policy="builtin",
    )
    manager = Manager(
        pg=ProcessGroupHost(timeout=30.0),
        load_state_dict=lambda sd: None,
        state_dict=lambda: {"params": state["params"]},
        min_replica_size=1,
        replica_id="policy_bench",
        lighthouse_addr=f"127.0.0.1:{lh.port}",
        timeout=30.0,
    )
    step_times = []
    policy_intents = 0.0
    try:
        for _ in range(steps):
            x, y = make_batch()
            t0 = time.perf_counter()
            manager.start_quorum()
            loss, grads = grad_fn(state["params"], x, y)
            reduced = manager.allreduce(grads).get_future().wait(timeout=60)
            if manager.should_commit():
                updates, new_opt = optimizer.update(
                    grads, state["opt_state"], state["params"]
                )
                state["params"] = optax.apply_updates(state["params"], updates)
                state["opt_state"] = new_opt
            float(loss)
            step_times.append(time.perf_counter() - t0)
            time.sleep(0.05)  # give the 0.2 s policy cadence room to fire
        # a calm 1-replica fleet trips the builtin calm-tighten-eject rule,
        # so at least one versioned frame must have reached the safe point
        deadline = time.time() + 10.0
        while time.time() < deadline:
            manager.start_quorum()
            policy_intents = manager.timings().get("policy_intents", 0.0)
            if policy_intents > 0:
                break
            time.sleep(0.2)
    finally:
        manager.shutdown(wait=False)
        lh.shutdown()
        os.environ.pop("TORCHFT_POLICY", None)
        os.environ.pop("TORCHFT_POLICY_INTERVAL_S", None)
    managed_step_ms = _median(step_times[2:]) * 1000.0

    # the fold runs once per TORCHFT_POLICY_INTERVAL_S (default 5 s) off
    # the training hot path; its duty cycle IS the amortized per-step cost
    # fraction, whatever the step time
    default_interval_ms = 5000.0
    duty_pct = fold_eval_ms / default_interval_ms * 100.0

    return {
        "policy_fold_eval_ms": round(fold_eval_ms, 3),
        "policy_fold_duty_cycle_pct": round(duty_pct, 4),
        "managed_step_ms": round(managed_step_ms, 3),
        "replay_events_per_s": round(replay_events_per_s, 1),
        "replay_wall_s": round(replay_s, 3),
        "replay_ranking": [
            {"policy": r["policy"], "score": r["score"]} for r in ranking
        ],
        "replay_winner": ranking[0]["policy"] if ranking else None,
        "policy_intents": policy_intents,
        "fixture_events": n_events,
        "fixture_replicas": N_REPLICAS,
        "steps": steps,
        "smoke": smoke,
    }


if __name__ == "__main__":
    if "--regen" in sys.argv[1:]:
        write_fixture()
        print(f"wrote {FIXTURE}")
        sys.exit(0)
    print(json.dumps(run(smoke="--smoke" in sys.argv[1:])))
