"""Tracing-plane cost on the real example trainer + /metrics under load.

The fleet tracing pitch is spans cheap enough to leave on by default: a
span record is one O(1) dict append behind one lock, and the Prometheus
registry only syncs gauges when a scrape actually arrives. This harness
measures that claim instead of asserting it, three ways in one run:

- **managed loop with tracing + /metrics live**: the ft_overhead trainer
  (examples/train_ddp.py ``build_trainer``) under a Manager with the span
  recorder on and the manager-side /metrics endpoint serving, while
  scraper threads hammer ``GET /metrics`` until ``scrapes`` responses
  land — the under-load leg; every response must parse as Prometheus
  text.
- **direct per-span cost**: the exact record paths the hot loop runs
  (``span()`` context exit, ``record_rel``, ``instant``) timed in a tight
  loop; ``tracing_overhead_pct`` is per-span cost × observed spans/step
  as a share of the measured managed step — the number the <1% gate
  holds. (An end-to-end A/B of two full loops would measure the 1-vCPU
  host's scheduler, not the machinery — same reasoning as
  healthwatch_bench.)
- **coverage sanity**: the loop's spans must actually be in the ring
  (quorum + commit categories present) and a dump must merge into a
  valid Chrome trace — cost without coverage would be the worst trade.

    python benchmarks/tracing_bench.py

Prints one JSON line; ``bench.py --tracing`` runs it in a CPU-pinned
subprocess and merges the row into the bench artifact (committed as
BENCH_TRACE.json), and ``bench.py --tracing --smoke`` is the fast-tier
CI gate (tests/test_bench_smoke.py).
"""

import json
import os
import statistics
import sys
import threading
import time
import urllib.request

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))
sys.path.insert(0, os.path.join(os.path.dirname(__file__), "..", "examples"))


def _median(xs):
    return statistics.median(xs) if xs else 0.0


def _parse_prometheus(text: str) -> int:
    """Count series, raising on any malformed exposition line."""
    n = 0
    for line in text.splitlines():
        if not line or line.startswith("#"):
            continue
        _name, value = line.rsplit(" ", 1)
        float(value)
        n += 1
    return n


def run(steps: int = 30, warmup: int = 5, batch_size: int = 8,
        scrapers: int = 4, scrapes: int = 10000,
        span_calls: int = 2000) -> dict:
    """Time the example trainer under a tracing+metrics Manager while
    hammering /metrics, then micro-time the span record paths.

    Returns ``tracing_overhead_pct`` (spans-per-step × per-span cost as a
    share of the managed step), the scrape-under-load tallies, and the
    merged-trace sanity fields.
    """
    import optax

    from train_ddp import build_trainer

    from torchft_tpu.coordination import LighthouseServer
    from torchft_tpu.manager import Manager
    from torchft_tpu.observability import log_timing_event
    from torchft_tpu.process_group import ProcessGroupHost
    from torchft_tpu.tracing import merge_traces

    total = warmup + steps

    def apply_update(state, optimizer, grads):
        updates, new_opt_state = optimizer.update(
            grads, state["opt_state"], state["params"]
        )
        state["params"] = optax.apply_updates(state["params"], updates)
        state["opt_state"] = new_opt_state

    state, grad_fn, optimizer, make_batch = build_trainer(0, batch_size)
    lh = LighthouseServer(
        bind="127.0.0.1:0", min_replicas=1, join_timeout_ms=200,
        quorum_tick_ms=20, heartbeat_timeout_ms=2000,
    )
    manager = Manager(
        pg=ProcessGroupHost(timeout=30.0),
        load_state_dict=lambda sd: None,
        state_dict=lambda: {"params": state["params"]},
        min_replica_size=1,
        replica_id="trace_bench",
        lighthouse_addr=f"127.0.0.1:{lh.port}",
        timeout=30.0,
        heartbeat_interval=0.05,
        tracing=True,
        metrics_port=0,
    )
    metrics_url = f"http://127.0.0.1:{manager.metrics_port}/metrics"

    # /metrics under load: scraper threads hammer the endpoint through the
    # whole managed loop and keep going until the scrape budget is spent;
    # every response must parse (the gate asserts zero failures)
    stop = threading.Event()
    scrape_lock = threading.Lock()
    scrape_ms: list = []
    scrape_failures: list = []
    series_seen = [0]

    def scrape_loop():
        while not stop.is_set():
            with scrape_lock:
                if len(scrape_ms) + len(scrape_failures) >= scrapes:
                    return
            t0 = time.perf_counter()
            try:
                with urllib.request.urlopen(metrics_url, timeout=5.0) as resp:
                    body = resp.read().decode()
                n = _parse_prometheus(body)
                if n == 0:
                    raise RuntimeError("empty /metrics exposition")
                with scrape_lock:
                    series_seen[0] = max(series_seen[0], n)
                    scrape_ms.append((time.perf_counter() - t0) * 1000.0)
            except Exception as e:  # noqa: BLE001 — tallied, asserted below
                with scrape_lock:
                    scrape_failures.append(str(e)[:200])

    threads = [threading.Thread(target=scrape_loop, daemon=True)
               for _ in range(scrapers)]

    ft_times: list = []
    committed = 0
    try:
        for t in threads:
            t.start()
        for _ in range(total):
            x, y = make_batch()
            t0 = time.perf_counter()
            manager.start_quorum()
            loss, grads = grad_fn(state["params"], x, y)
            reduced = manager.allreduce(grads).get_future().wait(timeout=60)
            if manager.should_commit():
                apply_update(state, optimizer, reduced)
                committed += 1
            float(loss)
            ft_times.append(time.perf_counter() - t0)

        # snapshot BEFORE the micro-timing loop below: its bench spans
        # must not count toward the managed loop's spans-per-step
        loop_stats = manager.tracer.stats()

        # the loop's trace must be real: spans in the ring, categories the
        # taxonomy promises, and a dump that merges into valid Chrome JSON
        export = manager.tracer.export()
        cats = {s["cat"] for s in export["spans"]}
        trace = merge_traces([export])
        merged_events = len(trace["traceEvents"])

        # direct per-span cost of every hot-loop record shape, amortized
        t0 = time.perf_counter()
        for i in range(span_calls):
            with manager.tracer.span("bench_span", cat="commit"):
                pass
            pc = time.perf_counter()
            manager.tracer.record_rel(
                "bench_rel", cat="allreduce", t0_pc=pc - 1e-4, t1_pc=pc,
                bucket=i,
            )
            manager.tracer.instant("bench_instant", cat="rpc")
        span_cost_s = (time.perf_counter() - t0) / (span_calls * 3)

        # drain the scrape budget even if the loop finished first: "10k
        # scrapes answered" is the claim, and a short training loop must
        # not quietly shrink it
        deadline = time.monotonic() + 120.0
        while time.monotonic() < deadline:
            with scrape_lock:
                if len(scrape_ms) + len(scrape_failures) >= scrapes:
                    break
            time.sleep(0.05)
    finally:
        stop.set()
        for t in threads:
            t.join(timeout=5.0)
        manager.shutdown(wait=False)
        lh.shutdown()

    ft_step_s = _median(ft_times[warmup:])
    stats = loop_stats
    spans_per_step = stats["recorded"] / max(total, 1)
    overhead_s = span_cost_s * spans_per_step
    result = {
        "tracing_overhead_pct": round(
            overhead_s / ft_step_s * 100.0, 4
        ) if ft_step_s > 0 else None,
        "tracing_span_cost_us": round(span_cost_s * 1e6, 4),
        "tracing_spans_per_step": round(spans_per_step, 2),
        "trace_spans_recorded": int(stats["recorded"]),
        "trace_spans_dropped": int(stats["dropped"]),
        "trace_categories": sorted(cats),
        "trace_merged_events": merged_events,
        "ft_step_s": round(ft_step_s, 6),
        "metrics_scrapes_ok": len(scrape_ms),
        "metrics_scrapes_failed": len(scrape_failures),
        "metrics_scrape_p50_ms": round(_median(scrape_ms), 3),
        "metrics_series": series_seen[0],
        "steps": steps,
        "committed": committed,
        "batch_size": batch_size,
    }
    if scrape_failures:
        result["metrics_scrape_first_error"] = scrape_failures[0]
    # same artifact policy as the other rows: the measurement rides the
    # observability stream next to the snapshots it is about
    log_timing_event(phase="tracing_bench", replica_id="trace_bench",
                     **result)
    return result


if __name__ == "__main__":
    print(json.dumps(run()))
