"""Streamed vs serial managed allreduce on the host loopback plane.

PR 3's streaming bucket pipeline claims the managed allreduce stops paying
pack → wire → unpack serially once buckets flow through the 3-stage
pipeline (bucket i+1 packs while bucket i rides the wire and bucket i−1
unpacks). This harness measures that claim instead of asserting it: two
replica groups exchange the SAME multi-bucket gradient tree through real
Managers (live lighthouse, per-step quorum + two-phase vote, loopback
ProcessGroupHost) twice — once with ``stream_buckets=False`` (PR 2's
monolithic path: one collective per plan, unpack after the LAST bucket's
wire) and once with the streaming pipeline — and reports the median step
walls side by side plus the pipeline's own stage splits
(``allreduce_pack_s`` / ``wire_s`` / ``unpack_s``) and
``overlap_efficiency`` (fraction of wire time hidden behind other buckets'
stages) from ``Manager.timings()``.

On the 1-vCPU bench hosts the win is cache locality + pipelining across
the PG dispatch / staging / unpack threads, not parallel silicon — medians
throughout, same policy as the other harnesses.

    python benchmarks/allreduce_pipeline_bench.py [--size-mb 64] [--cap-mb 4]

Prints one JSON line; ``bench.py --allreduce-pipeline`` runs it in a
CPU-pinned subprocess and ``--allreduce-pipeline --smoke`` is the fast-tier
CI gate (tests/test_bench_smoke.py) asserting the per-bucket split keys.
"""

import argparse
import json
import os
import statistics
import sys
import threading
import time

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))

import numpy as np  # noqa: E402


def _median(xs):
    return statistics.median(xs) if xs else 0.0


def _make_tree(size_mb: float, leaves: int) -> dict:
    n_total = int(size_mb * (1 << 20)) // 4
    per = max(1, n_total // leaves)
    rng = np.random.RandomState(0)
    return {
        f"w{i}": rng.randn(per).astype(np.float32) for i in range(leaves)
    }


def _run_mode(
    stream: bool, tree: dict, cap_bytes: int, steps: int, warmup: int
) -> dict:
    from torchft_tpu.coordination import LighthouseServer
    from torchft_tpu.manager import Manager
    from torchft_tpu.process_group import ProcessGroupHost

    lh = LighthouseServer(
        bind="127.0.0.1:0", min_replicas=2, join_timeout_ms=5000,
        quorum_tick_ms=20, heartbeat_timeout_ms=5000,
    )
    barrier = threading.Barrier(2)
    step_times: list = []
    snaps: list = []
    errors: list = []

    def replica(rid: int) -> None:
        manager = None
        try:
            manager = Manager(
                pg=ProcessGroupHost(timeout=60.0),
                load_state_dict=lambda sd: None,
                state_dict=lambda: {"x": np.zeros(1, np.float32)},
                min_replica_size=2,
                replica_id=f"pipeline_{'stream' if stream else 'serial'}_{rid}",
                lighthouse_addr=f"127.0.0.1:{lh.port}",
                timeout=60.0,
                bucket_cap_bytes=cap_bytes,
                stream_buckets=stream,
            )
            for i in range(steps):
                barrier.wait(timeout=180)
                t0 = time.perf_counter()
                manager.start_quorum()
                if stream:
                    manager.allreduce_streamed(tree).wait(timeout=120)
                else:
                    manager.allreduce(tree).get_future().wait(timeout=120)
                if not manager.should_commit():
                    errors.append(f"commit failed rid={rid} step={i}")
                if rid == 0:
                    step_times.append(time.perf_counter() - t0)
                    if i >= warmup:
                        snaps.append(manager.timings())
        except Exception as e:  # noqa: BLE001
            errors.append(f"rid={rid}: {type(e).__name__}: {e}")
            barrier.abort()
        finally:
            if manager is not None:
                manager.shutdown(wait=False)

    threads = [
        threading.Thread(target=replica, args=(rid,), daemon=True)
        for rid in (0, 1)
    ]
    try:
        for t in threads:
            t.start()
        for t in threads:
            t.join(timeout=600)
    finally:
        lh.shutdown()
    if errors:
        raise RuntimeError("; ".join(errors[:3]))

    out = {"step_s": round(_median(step_times[warmup:]), 6)}
    for key in (
        "allreduce_s",
        "allreduce_pack_s",
        "allreduce_wire_s",
        "allreduce_unpack_s",
        "allreduce_buckets",
        "overlap_efficiency",
    ):
        vals = [s[key] for s in snaps if key in s]
        if vals:
            out[key] = round(_median(vals), 6)
    return out


def run(
    size_mb: float = 64,
    leaves: int = 16,
    cap_mb: float = 4,
    steps: int = 10,
    warmup: int = 3,
) -> dict:
    """Time the two-replica loopback exchange serial vs streamed.

    Returns the serial/streamed median step walls, ``speedup_pct``
    ((serial − streamed) / serial), and the streamed run's pipeline stage
    splits + ``overlap_efficiency``.
    """
    from torchft_tpu.observability import log_timing_event

    tree = _make_tree(size_mb, leaves)
    cap_bytes = int(cap_mb * (1 << 20))

    serial = _run_mode(False, tree, cap_bytes, steps, warmup)
    streamed = _run_mode(True, tree, cap_bytes, steps, warmup)

    serial_s, streamed_s = serial["step_s"], streamed["step_s"]
    result = {
        "serial_step_s": serial_s,
        "streamed_step_s": streamed_s,
        "speedup_pct": round((serial_s - streamed_s) / serial_s * 100.0, 2)
        if serial_s > 0
        else None,
        "allreduce_pack_s": streamed.get("allreduce_pack_s"),
        "allreduce_wire_s": streamed.get("allreduce_wire_s"),
        "allreduce_unpack_s": streamed.get("allreduce_unpack_s"),
        "allreduce_buckets": streamed.get("allreduce_buckets"),
        "overlap_efficiency": streamed.get("overlap_efficiency"),
        "serial_allreduce_s": serial.get("allreduce_s"),
        "streamed_allreduce_s": streamed.get("allreduce_s"),
        "size_mb": size_mb,
        "leaves": leaves,
        "cap_mb": cap_mb,
        "steps": steps,
    }
    # ride the observability stream so fleet tooling sees the measured
    # pipeline win next to the per-step allreduce_pipeline snapshots
    log_timing_event(phase="allreduce_pipeline_bench",
                     replica_id="pipeline_bench", **result)
    return result


if __name__ == "__main__":
    p = argparse.ArgumentParser()
    p.add_argument("--size-mb", type=float, default=64)
    p.add_argument("--leaves", type=int, default=16)
    p.add_argument("--cap-mb", type=float, default=4)
    p.add_argument("--steps", type=int, default=10)
    p.add_argument("--warmup", type=int, default=3)
    a = p.parse_args()
    print(json.dumps(run(a.size_mb, a.leaves, a.cap_mb, a.steps, a.warmup)))
