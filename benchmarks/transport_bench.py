"""Host-plane data-path benchmarks.

1. Checkpoint transports (reference: checkpointing/pg_transport_bench.py and
   http_transport_bench.py — 12GB state dict timed over
   send_checkpoint/recv_checkpoint), with peak-RSS delta:

    python benchmarks/transport_bench.py --transport http --size-mb 1024
    python benchmarks/transport_bench.py --transport pg --size-mb 1024 --inplace

2. Cross-replica-group allreduce: the ring (reduce-scatter + allgather over
   raw frames) vs the naive full-mesh exchange, across world sizes, with
   measured per-rank bytes — the ring's traffic must be ~2x payload and
   world-size-independent:

    python benchmarks/transport_bench.py --transport allreduce --size-mb 64

Prints one JSON line per run.
"""

import argparse
import json
import os
import resource
import sys
import time
from concurrent.futures import ThreadPoolExecutor

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))

import numpy as np  # noqa: E402


def _rss_mb() -> float:
    """Peak RSS of THIS process. VmHWM, not ru_maxrss: on Linux ru_maxrss
    survives fork+exec, so a subprocess inherits its parent's peak and the
    two-process bench would report a zero receiver delta."""
    try:
        with open("/proc/self/status") as f:
            for line in f:
                if line.startswith("VmHWM"):
                    return int(line.split()[1]) / 1024  # KiB -> MiB
    except OSError:
        pass
    div = 1 << 20 if sys.platform == "darwin" else 1024
    return resource.getrusage(resource.RUSAGE_SELF).ru_maxrss / div


# one leaf size for the synthetic state, its template, and the --check leaf
# bound: these three must agree or the in-place template stops matching the
# sender's leaves and the regression guard computes the wrong ceiling
CHUNK_MB = 64


def _leaf_sizes(size_mb: int, chunk_mb: int = CHUNK_MB):
    """(n_chunks, floats_per_chunk) for a ~size_mb tree of chunk_mb leaves."""
    n_chunks = max(1, size_mb // chunk_mb)
    return n_chunks, size_mb * (1 << 20) // n_chunks // 4


def make_state(size_mb: int, chunk_mb: int = CHUNK_MB) -> dict:
    """A state pytree of ~size_mb in chunk_mb float32 leaves (mimics a
    sharded param/optimizer tree)."""
    n_chunks, per = _leaf_sizes(size_mb, chunk_mb)
    rng = np.random.RandomState(0)
    return {
        f"layer_{i}": rng.randn(per).astype(np.float32) for i in range(n_chunks)
    }


def make_template(size_mb: int, chunk_mb: int = CHUNK_MB) -> dict:
    """Same tree shape as ``make_state`` but zero-filled without the RNG —
    the in-place receiver must not inflate its RSS baseline (or its startup
    time) with a full random regeneration before the measurement.

    ``np.full`` rather than ``np.zeros``: zeros is calloc-lazy, so the
    template's pages would only become resident when the in-place copy
    writes them — charging the template's own footprint to the receive
    phase. A real trainer's live state is resident; make the template so.
    """
    n_chunks, per = _leaf_sizes(size_mb, chunk_mb)
    return {
        "user": {
            f"layer_{i}": np.full(per, 0, np.float32) for i in range(n_chunks)
        }
    }


def bench_http(state: dict, num_chunks: int, timeout: float) -> float:
    from torchft_tpu.checkpointing import HTTPTransport

    send = HTTPTransport(timeout=timeout, num_chunks=num_chunks)
    recv = HTTPTransport(timeout=timeout, num_chunks=num_chunks)
    try:
        t0 = time.perf_counter()
        with ThreadPoolExecutor(max_workers=1) as ex:
            sf = ex.submit(
                send.send_checkpoint,
                dst_ranks=[1], step=1, state_dict={"user": state}, timeout=timeout,
            )
            got = recv.recv_checkpoint(
                src_rank=0, metadata=send.metadata(), step=1, timeout=timeout
            )
            sf.result(timeout=timeout)
        dt = time.perf_counter() - t0
        assert set(got["user"]) == set(state)
        return dt
    finally:
        send.shutdown()
        recv.shutdown()


def bench_pg(state: dict, inplace: bool, timeout: float) -> float:
    from torchft_tpu.checkpointing import PGTransport
    from torchft_tpu.coordination import KvStoreServer
    from torchft_tpu.process_group import ProcessGroupHost

    store = KvStoreServer("127.0.0.1:0")
    pgs = [ProcessGroupHost(timeout=timeout) for _ in range(2)]
    addr = f"127.0.0.1:{store.port}/bench"
    with ThreadPoolExecutor(max_workers=2) as ex:
        list(ex.map(lambda r: pgs[r].configure(addr, r, 2, quorum_id=1), range(2)))

    template = (
        {"user": {k: np.zeros_like(v) for k, v in state.items()}} if inplace else None
    )
    sender = PGTransport(pgs[0], timeout=timeout)
    receiver = PGTransport(
        pgs[1], timeout=timeout,
        state_dict_template=(lambda: template) if inplace else None,
    )
    try:
        t0 = time.perf_counter()
        with ThreadPoolExecutor(max_workers=1) as ex:
            sf = ex.submit(
                sender.send_checkpoint,
                dst_ranks=[1], step=1, state_dict={"user": state}, timeout=timeout,
            )
            got = receiver.recv_checkpoint(
                src_rank=0, metadata=sender.metadata(), step=1, timeout=timeout
            )
            sf.result(timeout=timeout)
        dt = time.perf_counter() - t0
        assert set(got["user"]) == set(state)
        return dt
    finally:
        sender.shutdown()
        receiver.shutdown()
        for pg in pgs:
            pg.shutdown()
        store.shutdown()


def _add_steady_stats(stats: dict, recv_stats: dict, size_mb: int) -> None:
    """Fold the child's per-round times into the report: round 1 is the
    headline, min of the later rounds is the steady state."""
    if "seconds_rounds" in recv_stats:
        stats["seconds_rounds"] = recv_stats["seconds_rounds"]
        steady = min(recv_stats["seconds_rounds"][1:])
        stats["seconds_steady"] = steady
        stats["gb_per_s_steady"] = round(size_mb / 1024 / steady, 3)


def bench_pg_two_process(size_mb: int, timeout: float, inplace: bool,
                         repeat: int = 1,
                         snapshot_send: bool = True) -> dict:
    """Per-side RSS for the PG transport: parent = rank 0 sender, child =
    rank 1 receiver, each its own process over a shared KV store. With
    ``inplace`` the child preallocates a template and receives into it.

    ``repeat`` > 1 heals the same pair repeatedly (the production pattern —
    a live template absorbs every heal). Round 1 pays this host's
    first-touch page-fault tax on freshly allocated buffers (see
    docs/performance.md "microVM paging"); the steady-state rounds measure
    the transport itself."""
    import subprocess

    from torchft_tpu.checkpointing import PGTransport
    from torchft_tpu.coordination import KvStoreServer
    from torchft_tpu.process_group import ProcessGroupHost

    state = make_state(size_mb)
    payload_mb = sum(v.nbytes for v in state.values()) / 2**20
    store = KvStoreServer("127.0.0.1:0")
    addr = f"127.0.0.1:{store.port}/bench2p"
    child = subprocess.Popen(
        [sys.executable, os.path.abspath(__file__), "--transport", "pg",
         "--size-mb", str(size_mb), "--timeout", str(timeout),
         "--repeat", str(repeat),
         *(["--inplace"] if inplace else []),
         "--_recv-child", f"pg:{addr}"],
        stdout=subprocess.PIPE, stderr=subprocess.PIPE, text=True,
    )
    pg = ProcessGroupHost(timeout=timeout)
    # snapshot_send=False is the zero-copy row: this sender mutates nothing
    # mid-stream, which is the contract that mode requires
    sender = PGTransport(pg, timeout=timeout, snapshot_send=snapshot_send)
    try:
        rss_before = _rss_mb()
        pg.configure(addr, 0, 2, quorum_id=1)  # rendezvous with the child
        for r in range(repeat):
            sender.send_checkpoint(
                dst_ranks=[1], step=r + 1, state_dict={"user": state},
                timeout=timeout,
            )
        sender_delta = _rss_mb() - rss_before
        try:
            out, err = child.communicate(timeout=timeout + 120)
        except subprocess.TimeoutExpired:
            child.kill()
            out, err = child.communicate()
            sys.exit(f"pg recv child wedged:\n{err[-2000:]}")
        if child.returncode != 0:
            sys.exit(f"pg recv child failed:\n{err[-2000:]}")
        recv_stats = json.loads(out.strip().splitlines()[-1])
    finally:
        # a parent-side failure (configure timeout, send error) must not
        # orphan the child blocked in recv for its full timeout
        if child.poll() is None:
            child.kill()
            child.communicate()
        sender.shutdown()
        pg.shutdown()
        store.shutdown()
    stats = {
        "transport": "pg-2proc",
        "size_mb": size_mb,
        "inplace": inplace,
        "seconds": recv_stats["seconds"],
        "gb_per_s": round(size_mb / 1024 / recv_stats["seconds"], 3),
        "sender_send_rss_x_payload": round(sender_delta / payload_mb, 2),
        "receiver_rss_x_payload": round(
            recv_stats["rss_delta_mb"] / payload_mb, 2
        ),
    }
    _add_steady_stats(stats, recv_stats, size_mb)
    print(json.dumps(stats), flush=True)
    return stats


def _verify_and_report_recv(got: dict, dt: float, delta: float,
                            rounds: "list | None" = None) -> None:
    """Shared tail of both recv children: verify content cheaply (make_state
    seeds RandomState(0) and layer_0 is its first draw, so the first 64
    values match regardless of total size — no multi-GB regeneration after
    the measurement), then print the stats the parent parses."""
    expect = np.random.RandomState(0).randn(64).astype(np.float32)
    np.testing.assert_array_equal(got["user"]["layer_0"][:64], expect)
    stats = {"seconds": round(dt, 3), "rss_delta_mb": round(delta, 1)}
    if rounds is not None and len(rounds) > 1:
        stats["seconds_rounds"] = rounds
    print(json.dumps(stats))


def _pg_recv_child(addr: str, size_mb: int, timeout: float, inplace: bool,
                   repeat: int = 1) -> None:
    from torchft_tpu.checkpointing import PGTransport
    from torchft_tpu.process_group import ProcessGroupHost

    template = make_template(size_mb) if inplace else None
    pg = ProcessGroupHost(timeout=timeout)
    recv = PGTransport(
        pg, timeout=timeout,
        state_dict_template=(lambda: template) if inplace else None,
    )
    rounds = []
    try:
        pg.configure(addr, 1, 2, quorum_id=1)
        rss0 = _rss_mb()
        for r in range(repeat):
            t0 = time.perf_counter()
            got = recv.recv_checkpoint(
                src_rank=0, metadata=recv.metadata(), step=r + 1,
                timeout=timeout,
            )
            rounds.append(round(time.perf_counter() - t0, 3))
        delta = _rss_mb() - rss0
    finally:
        recv.shutdown()
        pg.shutdown()
    _verify_and_report_recv(got, rounds[0], delta, rounds)


def bench_http_two_process(size_mb: int, num_chunks: int, timeout: float,
                           inplace: bool = False, repeat: int = 1) -> dict:
    """Per-SIDE peak RSS (the streaming bound is ~1x payload + one leaf per
    side; the single-process bench necessarily shows ~2x because both ends
    share one address space). Parent stages + serves; a fresh child fetches
    and reports its own delta."""
    import subprocess

    from torchft_tpu.checkpointing import HTTPTransport

    state = make_state(size_mb)
    payload_mb = sum(v.nbytes for v in state.values()) / 2**20
    rss_before_stage = _rss_mb()
    send = HTTPTransport(timeout=timeout, num_chunks=num_chunks)
    try:
        send.send_checkpoint(
            dst_ranks=[1], step=1, state_dict={"user": state}, timeout=timeout
        )
        sender_delta = _rss_mb() - rss_before_stage
        child = subprocess.Popen(
            [sys.executable, os.path.abspath(__file__), "--transport",
             "http", "--size-mb", str(size_mb),
             "--num-chunks", str(num_chunks),
             "--timeout", str(timeout), "--repeat", str(repeat),
             *(["--inplace"] if inplace else []),
             "--_recv-child", send.metadata()],
            stdout=subprocess.PIPE, stderr=subprocess.PIPE, text=True,
        )
        # restage per round: disallow_checkpoint waits (bounded) for the
        # child to finish fetching the staged step before the swap, so the
        # child's retry loop only ever spans the restage gap
        for r in range(1, repeat):
            # dead child: let communicate() surface its stderr now instead
            # of stalling grace=timeout for each remaining round
            if child.poll() is not None:
                break
            # full-timeout grace: the child may still be allocating its
            # template before its first fetch; a short grace would restage
            # early and strand the child's step-r retry loop
            send.disallow_checkpoint(grace=timeout)
            send.send_checkpoint(
                dst_ranks=[1], step=r + 1, state_dict={"user": state},
                timeout=timeout,
            )
        try:
            out, err = child.communicate(
                # budget beyond the fetch timeout: interpreter/numpy
                # startup and the post-measurement payload verification
                timeout=timeout + 120,
            )
        except subprocess.TimeoutExpired:
            child.kill()
            out, err = child.communicate()
            sys.exit(f"recv child wedged past {timeout + 120}s:\n{err[-2000:]}")
        if child.returncode != 0:
            sys.exit(f"recv child failed:\n{err[-2000:]}")
        recv_stats = json.loads(out.strip().splitlines()[-1])
    finally:
        send.shutdown()
    stats = {
        "transport": "http-2proc",
        "size_mb": size_mb,
        "inplace": inplace,
        "seconds": recv_stats["seconds"],
        "gb_per_s": round(size_mb / 1024 / recv_stats["seconds"], 3),
        "sender_stage_rss_x_payload": round(sender_delta / payload_mb, 2),
        "receiver_rss_x_payload": round(
            recv_stats["rss_delta_mb"] / payload_mb, 2
        ),
    }
    _add_steady_stats(stats, recv_stats, size_mb)
    print(json.dumps(stats), flush=True)
    return stats


def _recv_child(metadata: str, size_mb: int, num_chunks: int, timeout: float,
                inplace: bool = False, repeat: int = 1) -> None:
    """Receiver half of the two-process bench: fetch, verify, report RSS."""
    import urllib.error

    from torchft_tpu.checkpointing import HTTPTransport

    template = make_template(size_mb) if inplace else None
    recv = HTTPTransport(
        timeout=timeout, num_chunks=num_chunks,
        state_dict_template=(lambda: template) if inplace else None,
    )
    rounds = []
    try:
        rss0 = _rss_mb()
        for r in range(repeat):
            # the sender restages between rounds; retry through the gap
            # where step r+1 is not yet staged (metadata fetch 400s)
            deadline = time.monotonic() + timeout
            t0 = time.perf_counter()
            while True:
                try:
                    got = recv.recv_checkpoint(
                        src_rank=0, metadata=metadata, step=r + 1,
                        timeout=timeout,
                    )
                    break
                except urllib.error.HTTPError:
                    if time.monotonic() > deadline:
                        raise
                    time.sleep(0.05)
                    t0 = time.perf_counter()  # don't bill the restage gap
            rounds.append(round(time.perf_counter() - t0, 3))
        delta = _rss_mb() - rss0
    finally:
        recv.shutdown()
    _verify_and_report_recv(got, rounds[0], delta, rounds)


def bench_allreduce(size_mb: int, timeout: float) -> None:
    """Ring vs naive exchange across world sizes, with per-rank bytes from
    the _Comm traffic counters (VERDICT round-2 item 2's 'Done' numbers)."""
    import torchft_tpu.process_group as pg_mod
    from torchft_tpu.coordination import KvStoreServer
    from torchft_tpu.process_group import ProcessGroupHost, ReduceOp

    n = size_mb * (1 << 20) // 4
    payload = n * 4
    for world in (2, 4):
        for algo in ("ring", "naive", "fp8"):
            store = KvStoreServer("127.0.0.1:0")
            pgs = [ProcessGroupHost(timeout=timeout) for _ in range(world)]
            addr = f"127.0.0.1:{store.port}/bench_ar"
            with ThreadPoolExecutor(world) as ex:
                list(ex.map(
                    lambda r: pgs[r].configure(addr, r, world, quorum_id=1),
                    range(world),
                ))
            old_thresh = pg_mod._RING_MIN_BYTES
            pg_mod._RING_MIN_BYTES = 0 if algo == "ring" else 1 << 62
            try:
                vals = [np.full(n, float(r + 1), np.float32) for r in range(world)]

                if algo == "fp8":
                    from torchft_tpu.collectives import allreduce_quantized

                    def step(r):
                        return (
                            allreduce_quantized(
                                [vals[r]], ReduceOp.SUM, pgs[r]
                            ).get_future().wait(timeout)
                        )
                else:
                    def step(r):
                        return (
                            pgs[r].allreduce([vals[r]], ReduceOp.SUM)
                            .get_future().wait(timeout)
                        )

                with ThreadPoolExecutor(world) as ex:  # warmup + correctness
                    outs = list(ex.map(step, range(world)))
                assert np.allclose(outs[0][0][:8], world * (world + 1) / 2)

                base = [pg._gen.comm.bytes_sent for pg in pgs]
                iters = 3
                t0 = time.perf_counter()
                for _ in range(iters):
                    with ThreadPoolExecutor(world) as ex:
                        list(ex.map(step, range(world)))
                dt = (time.perf_counter() - t0) / iters
                sent = max(
                    pg._gen.comm.bytes_sent - b for pg, b in zip(pgs, base)
                ) / iters
            finally:
                pg_mod._RING_MIN_BYTES = old_thresh
                for pg in pgs:
                    pg.shutdown()
                store.shutdown()
            print(json.dumps({
                "transport": "allreduce",
                "algo": algo,
                "world": world,
                "size_mb": size_mb,
                "seconds": round(dt, 4),
                "gbit_per_s": round(payload * 8 / dt / 1e9, 2),
                "per_rank_sent_x_payload": round(sent / payload, 2),
            }), flush=True)


def main() -> None:
    parser = argparse.ArgumentParser(description=__doc__)
    parser.add_argument(
        "--transport", choices=["http", "pg", "allreduce"], default="http"
    )
    parser.add_argument("--size-mb", type=int, default=256)
    parser.add_argument("--num-chunks", type=int, default=8,
                        help="http parallel chunk fetches")
    parser.add_argument("--inplace", action="store_true",
                        help="pg/http: receive into a preallocated template")
    parser.add_argument("--timeout", type=float, default=600.0)
    parser.add_argument("--two-process", action="store_true",
                        help="http/pg: sender and receiver in separate "
                             "processes, per-side peak RSS")
    parser.add_argument("--check", action="store_true",
                        help="two-process: exit 1 if a side's peak RSS "
                             "exceeds --rss-bound x payload (regression "
                             "guard for the streaming paths)")
    parser.add_argument("--rss-bound", type=float, default=1.15,
                        help="per-side peak-RSS/payload ceiling for --check "
                             "(streaming bound is ~1x + one leaf)")
    parser.add_argument("--inplace-recv-bound", type=float, default=0.15,
                        help="receiver-side ceiling for --check with "
                             "--inplace: the template absorbs the payload, "
                             "so receiver RSS growth must stay ~one leaf; "
                             "the general --rss-bound (~1x) would pass even "
                             "a fully-materializing regression")
    parser.add_argument("--no-snapshot-send", action="store_true",
                        help="pg: stream straight from the sender's arrays "
                             "(PGTransport snapshot_send=False — no "
                             "per-heal checkpoint copy; requires nothing "
                             "mutates state mid-stream)")
    parser.add_argument("--repeat", type=int, default=1,
                        help="two-process: heal the same pair N times; "
                             "rounds >1 report the steady state (round 1 "
                             "pays this host's first-touch paging tax)")
    parser.add_argument("--_recv-child", default="", help=argparse.SUPPRESS)
    args = parser.parse_args()

    if args.check and not args.two_process:
        # the single-process bench shares one address space (~2x RSS by
        # design) — a --check there would be meaningless, and silently
        # skipping it would be a green CI signal with no guard evaluated
        parser.error("--check requires --two-process (per-side RSS)")
    if (args.inplace and args.transport == "http" and not args.two_process
            and not args._recv_child):
        # the single-process http bench has no template path; silently
        # dropping the flag would report a non-inplace run as requested.
        # (_recv_child IS the receiver half of a two-process run — it gets
        # --inplace without --two-process and must not trip this guard.)
        parser.error("--transport http --inplace requires --two-process")
    if args._recv_child:
        if args._recv_child.startswith("pg:"):
            _pg_recv_child(args._recv_child[3:], args.size_mb, args.timeout,
                           args.inplace, args.repeat)
        else:
            _recv_child(args._recv_child, args.size_mb, args.num_chunks,
                        args.timeout, args.inplace, args.repeat)
        return
    if args.transport == "allreduce":
        bench_allreduce(args.size_mb, args.timeout)
        return
    if args.two_process:
        if args.transport == "http":
            stats = bench_http_two_process(
                args.size_mb, args.num_chunks, args.timeout, args.inplace,
                args.repeat,
            )
        else:  # "pg" — argparse choices exclude everything else
            stats = bench_pg_two_process(
                args.size_mb, args.timeout, args.inplace, args.repeat,
                snapshot_send=not args.no_snapshot_send,
            )
        if args.check:
            # in-place receive holds ~1-2 transient CHUNK_MB leaves besides
            # the resident template, so the receiver ceiling is
            # leaf-granular; budget THREE leaves — one more than the
            # worst-case legitimate transient — so allocator/measurement
            # noise can't flake the guard while a materializing regression
            # (1x+ payload) still fails by a wide margin. At 12 GB that's
            # ~0.016x payload, at 1 GB ~0.19x; below ~512 MB the ratio is
            # leaf-dominated and the check loses discriminating power.
            leaf_x_payload = 3 * float(CHUNK_MB) / max(args.size_mb, 1)

            # one leaf of slack for EVERY bound: at small payloads a single
            # transient 64 MB buffer coinciding with the peak is legitimate
            # noise, not a regression (at 12 GB the slack is ~0.005x)
            one_leaf = float(CHUNK_MB) / max(args.size_mb, 1)

            def bound_for(key: str) -> float:
                # gate on the stat the run actually produced, not the raw
                # flag (both http and pg two-process runs report it)
                if stats.get("inplace") and key == "receiver_rss_x_payload":
                    return max(args.inplace_recv_bound, leaf_x_payload)
                return args.rss_bound + one_leaf

            over = {
                k: (v, bound_for(k)) for k, v in stats.items()
                if k.endswith("rss_x_payload") and v > bound_for(k)
            }
            if over:
                sys.exit(
                    f"RSS regression: {over} exceeds its (value, bound)x "
                    "payload ceiling — a streaming/in-place path is "
                    "materializing the full checkpoint"
                )
        return

    state = make_state(args.size_mb)
    rss0 = _rss_mb()
    if args.transport == "http":
        dt = bench_http(state, args.num_chunks, args.timeout)
    else:
        dt = bench_pg(state, args.inplace, args.timeout)
    payload_mb = sum(v.nbytes for v in state.values()) / 2**20
    rss_delta = _rss_mb() - rss0
    print(json.dumps({
        "transport": args.transport,
        "size_mb": args.size_mb,
        "inplace": bool(args.inplace and args.transport == "pg"),
        "seconds": round(dt, 3),
        "gb_per_s": round(args.size_mb / 1024 / dt, 3),
        "peak_rss_delta_mb": round(rss_delta, 1),
        "rss_delta_x_payload": round(rss_delta / payload_mb, 2),
    }))


if __name__ == "__main__":
    main()
