"""Checkpoint-transport benchmarks (reference:
checkpointing/pg_transport_bench.py and http_transport_bench.py — 12GB state
dict timed over send_checkpoint/recv_checkpoint).

Times a send/recv of a synthetic state pytree between two endpoints on this
host, for both transports:

    python benchmarks/transport_bench.py --transport http --size-mb 1024
    python benchmarks/transport_bench.py --transport pg --size-mb 1024 --inplace

Prints one JSON line per run: {"transport", "size_mb", "seconds", "gb_per_s"}.
"""

import argparse
import json
import os
import sys
import time
from concurrent.futures import ThreadPoolExecutor

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))

import numpy as np  # noqa: E402


def make_state(size_mb: int, chunk_mb: int = 64) -> dict:
    """A state pytree of ~size_mb in chunk_mb float32 leaves (mimics a
    sharded param/optimizer tree)."""
    n_chunks = max(1, size_mb // chunk_mb)
    per = size_mb * (1 << 20) // n_chunks // 4
    rng = np.random.RandomState(0)
    return {
        f"layer_{i}": rng.randn(per).astype(np.float32) for i in range(n_chunks)
    }


def bench_http(state: dict, num_chunks: int, timeout: float) -> float:
    from torchft_tpu.checkpointing import HTTPTransport

    send = HTTPTransport(timeout=timeout, num_chunks=num_chunks)
    recv = HTTPTransport(timeout=timeout, num_chunks=num_chunks)
    try:
        t0 = time.perf_counter()
        with ThreadPoolExecutor(max_workers=1) as ex:
            sf = ex.submit(
                send.send_checkpoint,
                dst_ranks=[1], step=1, state_dict={"user": state}, timeout=timeout,
            )
            got = recv.recv_checkpoint(
                src_rank=0, metadata=send.metadata(), step=1, timeout=timeout
            )
            sf.result(timeout=timeout)
        dt = time.perf_counter() - t0
        assert set(got["user"]) == set(state)
        return dt
    finally:
        send.shutdown()
        recv.shutdown()


def bench_pg(state: dict, inplace: bool, timeout: float) -> float:
    from torchft_tpu.checkpointing import PGTransport
    from torchft_tpu.coordination import KvStoreServer
    from torchft_tpu.process_group import ProcessGroupHost

    store = KvStoreServer("127.0.0.1:0")
    pgs = [ProcessGroupHost(timeout=timeout) for _ in range(2)]
    addr = f"127.0.0.1:{store.port}/bench"
    with ThreadPoolExecutor(max_workers=2) as ex:
        list(ex.map(lambda r: pgs[r].configure(addr, r, 2, quorum_id=1), range(2)))

    template = (
        {"user": {k: np.zeros_like(v) for k, v in state.items()}} if inplace else None
    )
    sender = PGTransport(pgs[0], timeout=timeout)
    receiver = PGTransport(
        pgs[1], timeout=timeout,
        state_dict_template=(lambda: template) if inplace else None,
    )
    try:
        t0 = time.perf_counter()
        with ThreadPoolExecutor(max_workers=1) as ex:
            sf = ex.submit(
                sender.send_checkpoint,
                dst_ranks=[1], step=1, state_dict={"user": state}, timeout=timeout,
            )
            got = receiver.recv_checkpoint(
                src_rank=0, metadata=sender.metadata(), step=1, timeout=timeout
            )
            sf.result(timeout=timeout)
        dt = time.perf_counter() - t0
        assert set(got["user"]) == set(state)
        return dt
    finally:
        sender.shutdown()
        receiver.shutdown()
        for pg in pgs:
            pg.shutdown()
        store.shutdown()


def main() -> None:
    parser = argparse.ArgumentParser(description=__doc__)
    parser.add_argument("--transport", choices=["http", "pg"], default="http")
    parser.add_argument("--size-mb", type=int, default=256)
    parser.add_argument("--num-chunks", type=int, default=8,
                        help="http parallel chunk fetches")
    parser.add_argument("--inplace", action="store_true",
                        help="pg: receive into a preallocated template")
    parser.add_argument("--timeout", type=float, default=600.0)
    args = parser.parse_args()

    state = make_state(args.size_mb)
    if args.transport == "http":
        dt = bench_http(state, args.num_chunks, args.timeout)
    else:
        dt = bench_pg(state, args.inplace, args.timeout)
    print(json.dumps({
        "transport": args.transport,
        "size_mb": args.size_mb,
        "inplace": bool(args.inplace and args.transport == "pg"),
        "seconds": round(dt, 3),
        "gb_per_s": round(args.size_mb / 1024 / dt, 3),
    }))


if __name__ == "__main__":
    main()
