"""Redundancy-plane recovery benchmark: parallel erasure reconstruct vs
single-source heal, plus the steady-state cost of shard staging on the
managed step. Prints ONE JSON line; full runs also write
``BENCH_RECOVERY.json``.

    python benchmarks/redundancy_bench.py [--smoke]

NIC model (provenance — read before quoting numbers): this host is one
1-vCPU loopback box, so raw socket throughput says nothing about a pod.
Every ShardStore GET is sleep-throttled to ``--nic-mb-s`` per holder —
the stand-in for per-peer NIC egress. A single-source heal drains ONE
holder's egress cap serially; the parallel reconstruct drains k+m
holders concurrently, so the transfer-bound expectation is ~k x at
large sizes. What this host pays HONESTLY on top: crc32 verification,
the GF(256) decode, and state unpack all run on the single vCPU and are
included in the parallel wall-clock — the measured speedup is therefore
a floor, not a cherry-pick. Absolute seconds are the model's, ratios
are the claim.

Phases:

- **curve**: for each size, stage the same packed state twice — as one
  k=1/m=0 whole-blob generation on one throttled holder (exactly the
  single-source heal wire) and as a k/m erasure generation across k+m
  throttled holders — then time ``reconstruct_state`` for each through
  the same directory + shard-store path, asserting bitwise-identical
  round-trips.
- **staging**: the commit-path cost. ``ShardStager.stage()`` (the exact
  call the Manager makes per commit: pack + newest-wins enqueue) is
  timed across a simulated train loop, and a real 2-replica managed
  fleet with redundancy ON measures the managed step gap it amortizes
  against. Overhead percent = mean stage() wall / median step gap.
"""

import argparse
import json
import os
import sys
import threading
import time
from concurrent.futures import ThreadPoolExecutor

REPO_ROOT = os.path.join(os.path.dirname(os.path.abspath(__file__)), "..")
sys.path.insert(0, REPO_ROOT)

import numpy as np  # noqa: E402

FULL_SIZES_MB = (64, 256, 1024)
SMOKE_SIZES_MB = (8,)


def _make_state(size_mb: int, seed: int = 0) -> dict:
    """One float32 leaf of ``size_mb`` built by tiling a 1 MiB random
    block — fast to generate at 1 GB, non-degenerate for crc32."""
    block = (
        np.random.RandomState(seed)
        .randint(0, 1 << 31, size=(1 << 18,), dtype=np.int64)
        .astype(np.float32)
    )
    reps = max(1, (size_mb * (1 << 20)) // block.nbytes)
    return {"w": np.tile(block, reps)}


def _stage_generation(client, owner, step, blob, k, m, stores):
    """Encode + PUT + announce one generation the way ShardStager does,
    returning (encode_s, put_s)."""
    from torchft_tpu.checkpointing.erasure import encode_shards, shard_crc
    from torchft_tpu.redundancy import put_shard

    t0 = time.monotonic()
    shards = encode_shards(blob, k, m)
    encode_s = time.monotonic() - t0
    epoch = client.register(owner, pod="bench", store_url=stores[0].url)
    entries = []
    t0 = time.monotonic()
    for idx, body in enumerate(shards):
        store = stores[idx % len(stores)]
        put_shard(store.url, owner, step, idx, body, timeout=600.0)
        entries.append(
            {
                "idx": idx,
                "holder": store.replica_id,
                "url": store.url,
                "crc": shard_crc(body),
            }
        )
    put_s = time.monotonic() - t0
    code, resp = client.announce(
        {
            "replica_id": owner,
            "epoch": epoch,
            "seq": 1,
            "step": step,
            "k": k,
            "m": m,
            "data_len": len(blob),
            "shards": entries,
        }
    )
    if code != 200:
        raise RuntimeError(f"bench announce rejected: {resp}")
    return encode_s, put_s


def reconstruct_point(size_mb: int, k: int, m: int, nic_mb_s: float) -> dict:
    """Single-source vs parallel reconstruct at one state size."""
    from torchft_tpu.redundancy import (
        DirectoryClient,
        ShardDirectory,
        ShardStore,
        pack_state_blob,
        reconstruct_state,
    )

    directory = ShardDirectory()
    client = DirectoryClient(directory.url, timeout=30.0)
    state = _make_state(size_mb)
    blob = pack_state_blob(state)
    single_store = ShardStore("bench_single_holder", throttle_mb_s=nic_mb_s)
    par_stores = [
        ShardStore(f"bench_holder_{i}", throttle_mb_s=nic_mb_s)
        for i in range(k + m)
    ]
    try:
        encode_s, _ = _stage_generation(
            client, "bench_parallel", 1, blob, k, m, par_stores
        )
        _stage_generation(
            client, "bench_single", 1, blob, 1, 0, [single_store]
        )
        # the stores hold their own shard copies now; drop the staging blob
        # so neither timed leg pays for a bloated resident set
        del blob

        # each leg is timed, verified, then freed before the next leg runs:
        # a real heal reconstructs into a fresh worker, so neither mode
        # should be measured while a previous 1 GB result is pinned in RAM
        # (on virtualized hosts, fresh-page faults slow down with footprint)
        t0 = time.monotonic()
        _, got_single, stats_single = reconstruct_state(
            directory.url, owner="bench_single", timeout=1200.0,
            max_workers=1,
        )
        single_s = time.monotonic() - t0
        if not np.array_equal(np.asarray(got_single["w"]), state["w"]):
            raise RuntimeError(
                f"single reconstruct at {size_mb} MB is not bitwise-equal"
            )
        shards_ok_single = stats_single["shards_ok"]
        del got_single, stats_single

        t0 = time.monotonic()
        _, got_par, stats_par = reconstruct_state(
            directory.url, owner="bench_parallel", timeout=1200.0,
            max_workers=k + m,
        )
        parallel_s = time.monotonic() - t0
        if not np.array_equal(np.asarray(got_par["w"]), state["w"]):
            raise RuntimeError(
                f"parallel reconstruct at {size_mb} MB is not bitwise-equal"
            )
        shards_ok_parallel = stats_par["shards_ok"]
        del got_par, stats_par
    finally:
        single_store.shutdown()
        for s in par_stores:
            s.shutdown()
        directory.shutdown()

    mb = size_mb
    return {
        "size_mb": size_mb,
        "single_source_s": round(single_s, 3),
        "parallel_s": round(parallel_s, 3),
        "speedup_x": round(single_s / parallel_s, 2),
        "single_source_mb_s": round(mb / single_s, 1),
        "parallel_mb_s": round(mb / parallel_s, 1),
        "encode_s": round(encode_s, 3),
        "shards_ok_parallel": shards_ok_parallel,
        "shards_ok_single": shards_ok_single,
    }


def _managed_step_gap(
    state_mb: int, steps: int, compute_s: float, k: int, m: int,
    interval: int,
) -> float:
    """Median inter-commit gap of a real 2-replica managed fleet with the
    redundancy plane ON (stager attached, co-hosted directory)."""
    from torchft_tpu.coordination import LighthouseServer
    from torchft_tpu.manager import Manager
    from torchft_tpu.process_group import ProcessGroupHost

    lh = LighthouseServer(
        bind="127.0.0.1:0", min_replicas=1, join_timeout_ms=2000,
        quorum_tick_ms=20, heartbeat_timeout_ms=2000,
        redundancy_directory=True,
    )
    env_keys = {
        "TORCHFT_REDUNDANCY_K": str(k),
        "TORCHFT_REDUNDANCY_M": str(m),
        "TORCHFT_REDUNDANCY_DIRECTORY": lh.redundancy_directory_url(),
        "TORCHFT_REDUNDANCY_INTERVAL": str(interval),
    }
    saved = {kk: os.environ.get(kk) for kk in env_keys}
    os.environ.update(env_keys)
    n_elem = state_mb * (1 << 20) // 4
    commit_times: list = []

    def replica(rid: int) -> None:
        params = {"w": np.zeros(n_elem, dtype=np.float32)}
        manager = Manager(
            pg=ProcessGroupHost(timeout=30.0),
            load_state_dict=lambda sd: params.update(
                w=np.asarray(sd["w"], dtype=np.float32)
            ),
            state_dict=lambda: {"w": params["w"]},
            min_replica_size=1,
            use_async_quorum=True,
            replica_id=f"red_bench_{rid}",
            lighthouse_addr=f"127.0.0.1:{lh.port}",
            timeout=30.0,
            quorum_timeout=15.0,
        )
        grads = {"w": np.full(n_elem, 0.01, dtype=np.float32)}
        try:
            while manager.current_step() < steps:
                manager.start_quorum()
                time.sleep(compute_s)  # the simulated train step
                avg = manager.allreduce(grads).get_future().wait(120)
                if manager.should_commit():
                    params["w"] = params["w"] - np.asarray(avg["w"])
                    if rid == 0:
                        commit_times.append(time.monotonic())
        finally:
            manager.shutdown(wait=False)

    try:
        with ThreadPoolExecutor(max_workers=2) as ex:
            futs = [ex.submit(replica, r) for r in range(2)]
            for f in futs:
                f.result(timeout=600)
    finally:
        lh.shutdown()
        for kk, v in saved.items():
            if v is None:
                os.environ.pop(kk, None)
            else:
                os.environ[kk] = v
    gaps = np.diff(commit_times)
    if len(gaps) < 3:
        raise RuntimeError("not enough commits for a step-gap estimate")
    return float(np.median(gaps))


def staging_overhead(
    state_mb: int, steps: int, compute_s: float, k: int, m: int,
    interval: int,
) -> dict:
    """Hot-path stage() cost amortized over the managed step."""
    from torchft_tpu.redundancy import (
        DirectoryClient,
        RedundancyConfig,
        ShardDirectory,
        ShardStager,
        ShardStore,
    )

    directory = ShardDirectory()
    client = DirectoryClient(directory.url, timeout=10.0)
    stores = [ShardStore(f"bench_peer_{i}") for i in range(k + m)]
    for s in stores:
        client.register(s.replica_id, pod="bench", store_url=s.url)
    cfg = RedundancyConfig(
        k=k, m=m, directory=directory.url, interval=interval
    )
    stager = ShardStager(cfg, "bench_stage_owner")
    state = _make_state(state_mb, seed=1)
    costs = []
    try:
        for step in range(1, steps + 1):
            t0 = time.perf_counter()
            stager.stage(step, state)
            costs.append(time.perf_counter() - t0)
            time.sleep(compute_s)
        staged_to = stager.last_staged_step()
    finally:
        stager.shutdown()
        for s in stores:
            s.shutdown()
        directory.shutdown()

    step_gap_s = _managed_step_gap(
        state_mb, steps=max(6, steps // 2), compute_s=compute_s,
        k=k, m=m, interval=interval,
    )
    mean_stage_s = float(np.mean(costs))
    return {
        "staging_state_mb": state_mb,
        "staging_interval": interval,
        "stage_call_mean_s": round(mean_stage_s, 5),
        "stage_call_max_s": round(float(np.max(costs)), 5),
        "managed_step_s": round(step_gap_s, 4),
        "staging_overhead_pct": round(100.0 * mean_stage_s / step_gap_s, 3),
        # did the async worker keep pace with the commit cadence?
        "staging_kept_up": bool(staged_to >= steps - 2 * interval),
    }


def run(smoke: bool, nic_mb_s: float) -> dict:
    k, m = (4, 1) if smoke else (8, 2)
    sizes = SMOKE_SIZES_MB if smoke else FULL_SIZES_MB
    curve = [reconstruct_point(s, k, m, nic_mb_s) for s in sizes]
    at_max = curve[-1]
    if smoke:
        overhead = staging_overhead(
            state_mb=4, steps=6, compute_s=0.1, k=2, m=1, interval=2
        )
    else:
        overhead = staging_overhead(
            state_mb=64, steps=20, compute_s=0.8, k=2, m=1, interval=10
        )
    return {
        "recovery_k": k,
        "recovery_m": m,
        "recovery_nic_mb_s": nic_mb_s,
        "recovery_curve": curve,
        "recovery_size_mb_at_max": at_max["size_mb"],
        "recovery_single_source_s_at_max": at_max["single_source_s"],
        "recovery_parallel_s_at_max": at_max["parallel_s"],
        "recovery_reconstruct_speedup_x": at_max["speedup_x"],
        **overhead,
        "provenance": (
            "1-vCPU loopback host; per-holder NIC egress modeled by "
            f"sleep-throttling ShardStore GETs to {nic_mb_s} MB/s; crc32, "
            "GF(256) decode and state unpack run serially on the one vCPU "
            "and are included in the parallel wall-clock (speedup is a "
            "floor). Absolute seconds are the model's; ratios are the "
            "claim."
        ),
    }


def main(argv=None) -> None:
    parser = argparse.ArgumentParser(description=__doc__)
    parser.add_argument("--smoke", action="store_true")
    parser.add_argument("--nic-mb-s", type=float, default=40.0)
    parser.add_argument(
        "--out",
        default=os.path.join(REPO_ROOT, "BENCH_RECOVERY.json"),
        help="recovery-curve output path (full runs only; '-' disables)",
    )
    args = parser.parse_args(argv)

    result = run(smoke=args.smoke, nic_mb_s=args.nic_mb_s)
    if not args.smoke and args.out != "-":
        with open(args.out, "w") as f:
            json.dump(
                {
                    "bench": "redundancy plane (parallel reconstruct vs "
                    "single-source heal)",
                    "harness": "benchmarks/redundancy_bench.py",
                    **result,
                },
                f,
                indent=1,
                sort_keys=True,
            )
            f.write("\n")
        print(f"[redundancy_bench] wrote {args.out}", file=sys.stderr)

    print(json.dumps({
        "metric": "parallel reconstruct speedup over single-source heal",
        "value": result["recovery_reconstruct_speedup_x"],
        "unit": "x",
        "vs_baseline": result["recovery_reconstruct_speedup_x"],
        **{kk: v for kk, v in result.items() if kk != "recovery_curve"},
        "recovery_curve": result["recovery_curve"],
    }))


if __name__ == "__main__":
    main()
