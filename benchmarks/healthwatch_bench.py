"""Healthwatch cost on the real example trainer + /health under load.

The healthwatch pitch is telemetry at ~zero steady-state cost: the per-step
publish is one dict build and two lock hops, and the ledger fold rides the
heartbeat the Manager was already sending. This harness measures that claim
instead of asserting it, three ways in one run:

- **managed loop with the ledger live**: the ft_overhead trainer
  (examples/train_ddp.py ``build_trainer``) under a Manager whose lighthouse
  has the health ledger enabled (``mode=observe``), while poller threads
  hammer ``LighthouseClient.health()`` the whole time — the /health-under-load
  leg; every poll must parse.
- **direct per-step healthwatch cost**: the publish + summary-fold path
  (``Manager._publish_step_telemetry`` — private but ours; the bench pins the
  exact code the commit path runs) timed in a tight loop.
  ``healthwatch_overhead_pct`` is that per-call cost as a share of the
  measured managed step — the number the <1% gate holds. An end-to-end
  A/B of two full loops would be measuring the 1-vCPU host's scheduler, not
  the machinery: the direct timing is the stable form of the same claim.
- **ledger sanity**: after the loop the final /health payload must actually
  track the replica — cost without coverage would be the worst trade.

    python benchmarks/healthwatch_bench.py

Prints one JSON line; ``bench.py --healthwatch`` runs it in a CPU-pinned
subprocess and merges the row into the bench artifact, and
``bench.py --healthwatch --smoke`` is the fast-tier CI gate
(tests/test_bench_smoke.py).
"""

import json
import os
import statistics
import sys
import threading
import time

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))
sys.path.insert(0, os.path.join(os.path.dirname(__file__), "..", "examples"))


def _median(xs):
    return statistics.median(xs) if xs else 0.0


def run(steps: int = 30, warmup: int = 5, batch_size: int = 8,
        pollers: int = 2, publish_calls: int = 200) -> dict:
    """Time the example trainer under a health-enabled Manager while
    hammering /health, then micro-time the per-step healthwatch path.

    Returns ``healthwatch_overhead_pct`` (per-step publish+fold cost as a
    share of the managed step), the poll-under-load tallies, and the final
    ledger's view of the replica.
    """
    import optax

    from train_ddp import build_trainer

    from torchft_tpu.coordination import LighthouseClient, LighthouseServer
    from torchft_tpu.manager import Manager
    from torchft_tpu.observability import log_timing_event
    from torchft_tpu.process_group import ProcessGroupHost

    total = warmup + steps

    def apply_update(state, optimizer, grads):
        updates, new_opt_state = optimizer.update(
            grads, state["opt_state"], state["params"]
        )
        state["params"] = optax.apply_updates(state["params"], updates)
        state["opt_state"] = new_opt_state

    state, grad_fn, optimizer, make_batch = build_trainer(0, batch_size)
    lh = LighthouseServer(
        bind="127.0.0.1:0", min_replicas=1, join_timeout_ms=200,
        quorum_tick_ms=20, heartbeat_timeout_ms=2000,
        health={"mode": "observe"},
    )
    manager = Manager(
        pg=ProcessGroupHost(timeout=30.0),
        load_state_dict=lambda sd: None,
        state_dict=lambda: {"params": state["params"]},
        min_replica_size=1,
        replica_id="hw_bench",
        lighthouse_addr=f"127.0.0.1:{lh.port}",
        timeout=30.0,
        # beat fast enough that the short bench loop lands several
        # telemetry-carrying heartbeats in the ledger
        heartbeat_interval=0.05,
    )

    # /health under load: poller threads hammer the endpoint for the whole
    # managed loop; every response must parse (the client raises otherwise)
    stop = threading.Event()
    poll_ms: list = []
    poll_failures: list = []

    def poll_loop():
        client = LighthouseClient(f"127.0.0.1:{lh.port}", connect_timeout=5.0)
        while not stop.is_set():
            t0 = time.perf_counter()
            try:
                payload = client.health(timeout=5.0)
                if "replicas" not in payload:
                    raise RuntimeError(f"malformed /health payload: {payload}")
                poll_ms.append((time.perf_counter() - t0) * 1000.0)
            except Exception as e:  # noqa: BLE001 — tallied, asserted below
                poll_failures.append(str(e)[:200])

    threads = [threading.Thread(target=poll_loop, daemon=True)
               for _ in range(pollers)]

    ft_times: list = []
    committed = 0
    final_payload: dict = {}
    try:
        for t in threads:
            t.start()
        for _ in range(total):
            x, y = make_batch()
            t0 = time.perf_counter()
            manager.start_quorum()
            loss, grads = grad_fn(state["params"], x, y)
            reduced = manager.allreduce(grads).get_future().wait(timeout=60)
            if manager.should_commit():
                apply_update(state, optimizer, reduced)
                committed += 1
            float(loss)
            ft_times.append(time.perf_counter() - t0)
        # let at least one more telemetry-carrying beat land before reading
        # the ledger back
        time.sleep(0.15)
        final_payload = LighthouseClient(
            f"127.0.0.1:{lh.port}", connect_timeout=5.0
        ).health(timeout=5.0)

        # direct per-step cost of the healthwatch machinery: the exact
        # publish + summary-fold call the commit path runs, in a tight loop
        # (the ledger dedups repeated step numbers, so this is safe to spam)
        t0 = time.perf_counter()
        for _ in range(publish_calls):
            manager._publish_step_telemetry()
        publish_s = (time.perf_counter() - t0) / publish_calls
    finally:
        stop.set()
        for t in threads:
            t.join(timeout=5.0)
        manager.shutdown(wait=False)
        lh.shutdown()

    ft_step_s = _median(ft_times[warmup:])
    tracked = [k for k in final_payload.get("replicas", {})
               if k.startswith("hw_bench")]
    result = {
        "healthwatch_overhead_pct": round(
            publish_s / ft_step_s * 100.0, 4
        ) if ft_step_s > 0 else None,
        "healthwatch_publish_s": round(publish_s, 8),
        "ft_step_s": round(ft_step_s, 6),
        "health_polls_ok": len(poll_ms),
        "health_polls_failed": len(poll_failures),
        "health_poll_p50_ms": round(_median(poll_ms), 3),
        "health_replicas_tracked": len(tracked),
        "health_mode": final_payload.get("mode"),
        "steps": steps,
        "committed": committed,
        "batch_size": batch_size,
    }
    if poll_failures:
        result["health_poll_first_error"] = poll_failures[0]
    # same artifact policy as ft_overhead: the row rides the observability
    # stream so fleet tooling sees the measured cost next to the snapshots
    log_timing_event(phase="healthwatch_bench", replica_id="hw_bench",
                     **result)
    return result


if __name__ == "__main__":
    print(json.dumps(run()))
