"""Device-plane reconfiguration cost (VERDICT round-3 item 3).

The reference's whole design is a per-quorum communicator rebuild
(torchft/process_group.py:435-471: abort old NCCL comm -> new store prefix
-> new comm); its cost bounds how fast membership can change. This bench
times the TPU-native equivalents for every path a quorum change can take:

- **local**: ``ProcessGroupXLA(mode="local").configure`` — new mesh over
  surviving lead devices + fresh jit cache. Measured: first configure
  (fresh world build), the same-quorum re-enter (a second replica's
  configure hitting the process-global world registry), and the shrink
  reconfigure (new quorum id, fresh build).
- **distributed**: a real ``jax.distributed`` world per quorum, one process
  per replica (spawned fabric, one CPU device each — the same mechanism the
  spawned-process tests use). Measured per rank: initial world init, and
  the full teardown+reinit a membership change costs (the expensive,
  load-bearing path for real pods — ``jax.distributed.shutdown`` +
  backend clear + re-init with the new membership).
- **spares no-op**: under ``WorldSizeMode.FIXED_WITH_SPARES`` a spare's
  death changes nothing the compiled program can see; the steady-state cost
  is just the quorum RPC. Measured: median ``start_quorum`` latency across
  a stable 3-replica fleet with the world pinned at 2.

    python benchmarks/reconfigure_bench.py

Prints one JSON line; ``__graft_entry__.dryrun_multichip`` runs the same
measurements so the driver's MULTICHIP artifact records them.
"""

import json
import os
import queue
import statistics
import subprocess
import sys
import threading
import time

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

_DIST_TIMER = """\
import sys, time, json
sys.path.insert(0, {repo!r})
import jax
jax.config.update("jax_platforms", "cpu")
import jax.numpy as jnp
import numpy as np
from torchft_tpu.process_group import ReduceOp
from torchft_tpu.process_group_xla import ProcessGroupXLA

rank, world, port = int(sys.argv[1]), int(sys.argv[2]), sys.argv[3]
pg = ProcessGroupXLA(timeout=60.0, mode="distributed")
addr = f"127.0.0.1:{{port}}/reconf"

t0 = time.perf_counter()
pg.configure(addr, rank, world, quorum_id=1)
init_ms = (time.perf_counter() - t0) * 1e3
out = pg.allreduce([jnp.ones(4)], ReduceOp.SUM).get_future().wait(60)
assert np.allclose(np.asarray(out[0]), world)

# membership change: same world size re-keyed by quorum (worst case is the
# same as a shrink: full teardown + reinit either way)
t0 = time.perf_counter()
pg.configure(addr, rank, world, quorum_id=2)
reinit_ms = (time.perf_counter() - t0) * 1e3
out = pg.allreduce([jnp.full((4,), 2.0)], ReduceOp.SUM).get_future().wait(60)
assert np.allclose(np.asarray(out[0]), 2.0 * world)
pg.shutdown()
print("TIMING " + json.dumps({{"rank": rank, "init_ms": round(init_ms, 1),
                               "reinit_ms": round(reinit_ms, 1)}}), flush=True)
"""


def measure_local() -> dict:
    """Local-mode configure cost over the in-process device pool."""
    from torchft_tpu.coordination import KvStoreServer
    from torchft_tpu.process_group import ReduceOp
    from torchft_tpu.process_group_xla import ProcessGroupXLA

    import jax.numpy as jnp

    store = KvStoreServer("127.0.0.1:0")
    addr = f"127.0.0.1:{store.port}/reconf_local"
    try:
        pg = ProcessGroupXLA(timeout=30.0, mode="local")
        t0 = time.perf_counter()
        pg.configure(addr, 0, 2, quorum_id=1)
        first_ms = (time.perf_counter() - t0) * 1e3

        # same-quorum re-enter: the SECOND replica configuring into the
        # key the first replica's configure just built — the actual
        # registry-hit path (re-configuring the same PG instance would
        # poison its own world on teardown and measure a fresh rebuild)
        pg2 = ProcessGroupXLA(timeout=30.0, mode="local")
        t0 = time.perf_counter()
        pg2.configure(addr, 1, 2, quorum_id=1)
        reenter_ms = (time.perf_counter() - t0) * 1e3

        # a collective forces the jit path to materialize once
        w0 = pg.allreduce([jnp.ones(4)], ReduceOp.SUM)
        w1 = pg2.allreduce([jnp.ones(4)], ReduceOp.SUM)
        w0.get_future().wait(30), w1.get_future().wait(30)

        # shrink: quorum 2 drops rank 1
        t0 = time.perf_counter()
        pg.configure(addr, 0, 1, quorum_id=2)
        shrink_ms = (time.perf_counter() - t0) * 1e3
        pg.shutdown()
        pg2.shutdown()
    finally:
        store.shutdown()
    return {
        "local_first_ms": round(first_ms, 2),
        "local_shrink_ms": round(shrink_ms, 2),
        "local_reenter_ms": round(reenter_ms, 2),
    }


def measure_distributed(world: int = 2, timeout: float = 240.0) -> dict:
    """Spawn one process per rank; each times init and teardown+reinit."""
    from torchft_tpu.coordination import KvStoreServer

    store = KvStoreServer("127.0.0.1:0")
    env = dict(os.environ)
    env.pop("XLA_FLAGS", None)  # one CPU device per process
    script = _DIST_TIMER.format(repo=REPO)
    try:
        procs = [
            subprocess.Popen(
                [sys.executable, "-c", script, str(r), str(world),
                 str(store.port)],
                stdout=subprocess.PIPE, stderr=subprocess.STDOUT, text=True,
                env=env,
            )
            for r in range(world)
        ]
        timings = []
        fail = None
        for p in procs:
            try:
                out, _ = p.communicate(timeout=timeout)
            except subprocess.TimeoutExpired:
                p.kill()
                out, _ = p.communicate()
                fail = fail or f"rank timed out:\n{out[-2000:]}"
                continue
            for line in out.splitlines():
                if line.startswith("TIMING "):
                    timings.append(json.loads(line[len("TIMING "):]))
                    break
            else:
                fail = fail or f"rank exited rc={p.returncode}:\n{out[-2000:]}"
        if fail:
            # strict: world init/reinit are barriers, so a missing rank is
            # precisely the slow one — a partial max would undersell the cost
            raise RuntimeError(f"distributed timing failed: {fail}")
    finally:
        store.shutdown()
    return {
        "dist_world": world,
        "dist_init_ms": round(max(t["init_ms"] for t in timings), 1),
        "dist_reinit_ms": round(max(t["reinit_ms"] for t in timings), 1),
    }


def measure_spares_noop(steps: int = 6) -> dict:
    """Steady-state quorum latency with FIXED_WITH_SPARES (no reconfigure)."""
    from concurrent.futures import ThreadPoolExecutor

    from torchft_tpu.coordination import LighthouseServer
    from torchft_tpu.manager import Manager, WorldSizeMode
    from torchft_tpu.process_group_xla import ProcessGroupXLA

    lh = LighthouseServer(
        bind="127.0.0.1:0", min_replicas=3, join_timeout_ms=5000,
        quorum_tick_ms=20, heartbeat_timeout_ms=2000,
    )
    lat: dict = {}
    vote_rpc: dict = {}
    bookkeeping: dict = {}

    def replica(rid: int) -> None:
        manager = Manager(
            pg=ProcessGroupXLA(timeout=30.0, mode="local"),
            load_state_dict=lambda sd: None,
            state_dict=lambda: {},
            min_replica_size=2,
            use_async_quorum=False,
            replica_id=f"reconf_spares_{rid}",
            lighthouse_addr=f"127.0.0.1:{lh.port}",
            timeout=30.0,
            quorum_timeout=30.0,
            world_size_mode=WorldSizeMode.FIXED_WITH_SPARES,
        )
        times = []
        rpcs = []
        books = []
        try:
            for _ in range(steps):
                t0 = time.perf_counter()
                manager.start_quorum()
                times.append((time.perf_counter() - t0) * 1e3)
                manager.should_commit()
                t = manager.timings()
                if t.get("should_commit_rpc_s") is not None:
                    rpcs.append(t["should_commit_rpc_s"] * 1e3)
                if t.get("bookkeeping_s") is not None:
                    books.append(t["bookkeeping_s"] * 1e3)
            lat[rid] = times
            vote_rpc[rid] = rpcs
            bookkeeping[rid] = books
        finally:
            manager.shutdown(wait=False)

    try:
        with ThreadPoolExecutor(max_workers=3) as ex:
            futs = [ex.submit(replica, r) for r in range(3)]
            for f in futs:
                f.result(timeout=300)
    finally:
        lh.shutdown()
    # steady state = every quorum after the first (which pays join timeout)
    steady = [t for times in lat.values() for t in times[1:]]
    steady_rpc = [t for times in vote_rpc.values() for t in times[1:]]
    steady_book = [t for times in bookkeeping.values() for t in times[1:]]
    return {
        "spares_noop_quorum_ms": round(statistics.median(steady), 1),
        # per-step vote cost splits (Manager.timings()): the should_commit
        # RPC itself vs. everything else left on the hot path
        "spares_noop_vote_rpc_ms": round(statistics.median(steady_rpc), 3)
        if steady_rpc
        else None,
        "spares_noop_bookkeeping_ms": round(statistics.median(steady_book), 3)
        if steady_book
        else None,
    }


_RESTART_WORKER = """\
import sys, time, json
sys.path.insert(0, {repo!r})
import jax
jax.config.update("jax_platforms", "cpu")
import jax.numpy as jnp
import numpy as np
from torchft_tpu.process_group import ReduceOp
from torchft_tpu.process_group_xla import ProcessGroupXLA

role, port = sys.argv[1], sys.argv[2]
addr = f"127.0.0.1:{{port}}/restart"
pg = ProcessGroupXLA(timeout=15.0, mode="distributed")

if role in ("member0", "member1"):
    rank = int(role[-1])
    pg.configure(addr, rank, 2, quorum_id=1)
    out = pg.allreduce([jnp.ones(4)], ReduceOp.SUM).get_future().wait(30)
    assert np.allclose(np.asarray(out[0]), 2.0)
    print("PHASE steady", flush=True)
    time.sleep(600)  # rank 1 is killed; rank 0 waits for the runtime fatal
else:  # fresh0 / fresh1 — the restarted generation under quorum 2
    rank = int(role[-1])
    t0 = time.perf_counter()
    pg.configure(addr, rank, 2, quorum_id=2)
    join_ms = (time.perf_counter() - t0) * 1e3
    out = pg.allreduce([jnp.ones(4)], ReduceOp.SUM).get_future().wait(30)
    assert np.allclose(np.asarray(out[0]), 2.0)
    print("TIMING " + json.dumps({{"rank": rank,
                                   "join_ms": round(join_ms, 1)}}), flush=True)
"""


def measure_restart_mttr(timeout: float = 300.0) -> dict:
    """The restart-on-shrink recovery path, timed end to end on the real
    ``jax.distributed`` fabric.

    Toolchain invariant (process_group_xla._join_distributed_world): every
    member of a degraded distributed world dies — the coordination service
    pushes the peer-death error to all live pollers and jaxlib's handler is
    process-fatal. So the measured path is the one production takes: kill
    rank 1, time how long the runtime takes to terminate rank 0
    (``fatal_detect_ms``, bounded by TORCHFT_XLA_HEARTBEAT_SEC — a
    supervised trainer exits earlier on its own lighthouse signal), then
    respawn BOTH ranks cold into the next quorum and time
    interpreter+backend+world startup to the first allreduce
    (``cold_restart_ms``). The reference's BabyNCCL isolation
    (torchft/process_group.py:2042-2078) has no TPU equivalent — libtpu
    admits one process per chip — so this restart IS the isolation story
    (docs/operations.md)."""
    from torchft_tpu.coordination import KvStoreServer

    store = KvStoreServer("127.0.0.1:0")
    env = dict(os.environ)
    env.pop("XLA_FLAGS", None)
    script = _RESTART_WORKER.format(repo=REPO)

    def spawn(role):
        p = subprocess.Popen(
            [sys.executable, "-c", script, role, str(store.port)],
            stdout=subprocess.PIPE, stdin=subprocess.PIPE,
            stderr=subprocess.STDOUT, text=True, env=env, bufsize=1,
        )
        # lines arrive via a reader thread + queue so await_line's budget
        # bounds the WAIT, not just the line count — a worker that wedges
        # alive-but-silent (stuck runtime thread) must cost one timeout,
        # not hang the bench on a blocking readline
        p.lines = queue.Queue()
        def _pump(pipe, q):
            for line in pipe:
                q.put(line)
            q.put(None)  # EOF
        threading.Thread(
            target=_pump, args=(p.stdout, p.lines), daemon=True,
            name=f"reconfigure_bench_{role}_reader",
        ).start()
        return p

    def await_line(p, want, budget=timeout):
        t_end = time.monotonic() + budget
        while True:
            remaining = t_end - time.monotonic()
            if remaining <= 0:
                raise TimeoutError(f"no {want!r} within {budget}s")
            try:
                line = p.lines.get(timeout=remaining)
            except queue.Empty:
                raise TimeoutError(f"no {want!r} within {budget}s") from None
            if line is None:
                raise RuntimeError(
                    f"worker exited (rc={p.poll()}) waiting for {want!r}"
                )
            if line.startswith(want):
                return line

    workers = []

    def spawn_tracked(role):
        p = spawn(role)
        workers.append(p)
        return p

    m0 = spawn_tracked("member0")
    m1 = spawn_tracked("member1")
    try:
        await_line(m0, "PHASE steady")
        await_line(m1, "PHASE steady")

        t_kill = time.perf_counter()
        m1.kill()
        m1.wait(10)
        # the runtime terminates the survivor once the coordinator notices
        # the death (heartbeat window); a supervised trainer exits sooner
        # on its own detection, so this is the upper bound
        m0.wait(timeout)
        fatal_detect_ms = (time.perf_counter() - t_kill) * 1e3

        t_respawn = time.perf_counter()
        f0 = spawn_tracked("fresh0")
        f1 = spawn_tracked("fresh1")
        joins = {}
        for p in (f0, f1):
            line = await_line(p, "TIMING ")
            t = json.loads(line[len("TIMING "):])
            joins[t["rank"]] = t["join_ms"]
        cold_restart_ms = (time.perf_counter() - t_respawn) * 1e3
        f0.wait(30)
        f1.wait(30)
    finally:
        # every spawned generation: a TIMING wait that times out must not
        # orphan the fresh workers (live jax.distributed world) either
        for p in workers:
            if p.poll() is None:
                p.kill()
        store.shutdown()
    return {
        "restart_fatal_detect_ms": round(fatal_detect_ms, 1),
        "restart_cold_join_ms": round(max(joins.values()), 1),
        "restart_total_ms": round(fatal_detect_ms + cold_restart_ms, 1),
    }


def run() -> dict:
    out = {}
    out.update(measure_local())
    out.update(measure_distributed())
    out.update(measure_spares_noop())
    return out


def main() -> None:
    import argparse

    from torchft_tpu.utils import force_virtual_cpu_devices

    ap = argparse.ArgumentParser()
    ap.add_argument("--restart-mttr", action="store_true",
                    help="also time the launcher-restart escalation path "
                         "(kill + shrink + cold replacement join)")
    args = ap.parse_args()
    force_virtual_cpu_devices(2)
    out = run()
    if args.restart_mttr:
        out.update(measure_restart_mttr())
    print(json.dumps(out))


if __name__ == "__main__":
    main()
