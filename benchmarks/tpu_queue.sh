#!/bin/bash
# The round-5 TPU experiment queue. MANUAL INVOCATION ONLY, and only when
# ALL of these hold:
#   - the tunnel is healthy AND bench.py has already confirmed the
#     headline number on it (warm .jax_cache)
#   - there are HOURS of margin before the driver's round-end artifact
#     run: a sweep cell that hangs the compiler gets killed at its
#     timeout, and killing a remote compile is the known tunnel-wedge
#     trigger (rounds 3 and 4 both lost their artifact this way). Item 4
#     runs the two historically-pathological cells and goes LAST.
# Every cell is a subprocess inside mfu_sweep.py with a wall-clock
# timeout; the sweep re-probes the backend after any timeout and stops if
# the platform plugin has wedged.
#
# STATUS (round-5 continuation session): items 1-3 EXECUTED — results in
# docs/performance.md (uniform block 1024 wins the ladder; unroll-2 and
# every asymmetric tile lose; seq-8192 rows recorded). Item 4 was
# deliberately SKIPPED: a hang ends in a timeout kill (the wedge
# trigger) and the q2048 ladder cells already supplied the exact status
# code the item was after. The same session also ran the model/batch
# matrix (bench_1b/bench_2b) that produced the 0.538-MFU flagship —
# this file remains as the wedge-policy template for future queues.
#
# Queue (round-4 leftovers, docs/performance.md "queued experiments"):
#   1. splash block ladder incl. asymmetric q/kv tiles
#   2. --unroll 2 variant of the headline cell
#   3. long-context row: seq 8192, remat=full, batch 2, chunk 512
#   4. exact status codes for the two failing round-4 cells
set -u
cd /root/repo
LOG=${1:-/tmp/tpu_queue_r5.log}
{
  echo "=== tpu_queue start $(date -u +%FT%TZ)"
  echo "--- 1. splash block ladder (asymmetric q/kv included)"
  python benchmarks/mfu_sweep.py --blocks --timeout 1500
  echo "--- 2. unroll 2 on the headline cell"
  python benchmarks/mfu_sweep.py --unroll 2 --cell full,8,0 --timeout 1500
  echo "--- 3. long-context row seq=8192"
  python benchmarks/mfu_sweep.py --seq 8192 --cell full,2,512 --timeout 1800
  echo "--- 4. exact status codes for round-4 failing cells"
  python benchmarks/mfu_sweep.py --cell none,8,0 --cell dots,16,0 --timeout 1500
  echo "=== tpu_queue done $(date -u +%FT%TZ)"
} >> "$LOG" 2>&1
