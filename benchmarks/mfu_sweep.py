"""Sweep remat policy x batch size (and splash block size) for the
single-chip Llama bench.

Finds the config that maximizes MFU on the local chip; bench.py's settings
should track the winner. Uses bench.py's `timed_train_step` so the sweep
measures exactly the workload the headline bench reports. Run on TPU
hardware:
    python benchmarks/mfu_sweep.py            # remat x batch x chunk matrix
    python benchmarks/mfu_sweep.py --blocks   # splash block-size sweep

Every config runs in its OWN SUBPROCESS with a wall-clock timeout: a config
that wedges the compiler (observed on the round-3 toolchain: remat="attn"
with the splash kernel compiled >25 min and never returned) must cost one
timeout, not the rest of the matrix. After any timeout the parent re-probes
the backend and stops the sweep if the platform plugin itself has wedged —
launching more compiles at a dead tunnel only deepens the wedge.

remat="attn" is skipped from the full matrix unless TORCHFT_TPU_SWEEP_ATTN=1
(one observed compiler hang earns an opt-in gate even though the round-4
toolchain compiles it fine — see models/remat.py for the measured history);
targeted runs via --cell bypass the gate.
"""

import argparse
import itertools
import os
import subprocess
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

_CHILD = """\
import sys
sys.path.insert(0, {repo!r})
from bench import timed_train_step
from torchft_tpu.models.llama import CONFIGS
from torchft_tpu.ops import attention as _attn
tps, mfu = timed_train_step(CONFIGS[{cfg!r}], {batch}, {seq}, steps=10,
                            remat={remat!r}, loss_chunk={chunk},
                            master_f32={master_f32})
print(f"RESULT {{tps:.1f}} {{mfu:.4f}} {{_attn.LAST_DISPATCH}}", flush=True)
"""


def run_config(cfg, batch, seq, remat, chunk, env_extra, timeout_s,
               master_f32=False):
    """Run one sweep cell in a subprocess; returns a one-line verdict."""
    env = dict(os.environ, **env_extra)
    code = _CHILD.format(repo=REPO, cfg=cfg, batch=batch, seq=seq,
                         remat=remat, chunk=chunk, master_f32=master_f32)
    try:
        out = subprocess.run([sys.executable, "-c", code], env=env,
                             capture_output=True, text=True, timeout=timeout_s)
    except subprocess.TimeoutExpired:
        return None, f"TIMEOUT >{timeout_s:.0f}s (compiler wedge?)"
    for line in reversed(out.stdout.splitlines()):
        if line.startswith("RESULT "):
            _, tps, mfu, dispatch = line.split()
            return (float(tps), float(mfu), dispatch), None
    # surface the actual exception, not whatever JAX printed last: the
    # traceback's final exception line (or an XLA status code) is the root
    # cause; a blind tail usually lands on JAX's frame-filtering notice
    err_text = out.stderr.strip() or out.stdout.strip()
    cause = ""
    for line in reversed(err_text.splitlines()):
        if any(m in line for m in ("Error", "RESOURCE_EXHAUSTED", "INTERNAL",
                                   "INVALID_ARGUMENT", "UNIMPLEMENTED")):
            cause = line.strip()[:300]
            break
    return None, f"FAILED rc={out.returncode}: {cause or err_text[-300:]}"


def backend_alive() -> bool:
    from torchft_tpu.utils import probe_backend

    status, _ = probe_backend(90.0)
    return status in ("accel", "cpu")


def sweep(cells, timeout_s):
    """cells: iterable of (label, env_extra, kwargs for run_config)."""
    for label, env_extra, kw in cells:
        result, err = run_config(env_extra=env_extra, timeout_s=timeout_s, **kw)
        if result:
            tps, mfu, dispatch = result
            print(f"{label}: {tps:10.1f} tok/s  MFU={mfu:.4f}  [{dispatch}]",
                  flush=True)
        else:
            print(f"{label}: {err}", flush=True)
            if err.startswith("TIMEOUT") and not backend_alive():
                print("# backend no longer responds after the timeout — "
                      "stopping the sweep (wedged platform plugin)", flush=True)
                return


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--blocks", action="store_true",
                    help="sweep splash block sizes instead of the remat matrix")
    ap.add_argument("--timeout", type=float, default=1200.0,
                    help="per-config wall-clock budget (compile + warmup + "
                         "2 timed 10-step windows; a TIMEOUT kill is the "
                         "wedge-risk last resort — budget generously)")
    ap.add_argument("--unroll", type=int, default=0,
                    help="set TORCHFT_TPU_SCAN_UNROLL for every cell "
                         "(layer-scan unroll factor; 0 = leave unset)")
    ap.add_argument("--model", default="bench_350m",
                    help="CONFIGS key to bench (default bench_350m, the "
                         "cross-round headline config; bench_1b measures "
                         "the larger-matmul regime on the same chip)")
    ap.add_argument("--seq", type=int, default=2048,
                    help="sequence length (long-context cells: pair a "
                         "longer --seq with a smaller batch and a nonzero "
                         "CHUNK, e.g. --seq 8192 --cell full,2,512)")
    ap.add_argument("--cell", action="append", default=[],
                    metavar="REMAT,BATCH,CHUNK[,mf32]",
                    help="run only these cells (repeatable), e.g. "
                         "--cell full,16,0 --cell attn,8,0 --cell "
                         "full,8,0,mf32 (f32 master weights + moments); "
                         "bypasses the TORCHFT_TPU_SWEEP_ATTN gate (an "
                         "explicit cell is the opt-in)")
    args = ap.parse_args()

    # validate cell specs BEFORE the backend probe: an argv typo must cost
    # an argparse error, not a 90 s probe against a possibly-wedged tunnel
    cell_specs = []
    for spec in args.cell:
        parts = spec.split(",")
        if len(parts) < 3 or (len(parts) == 4 and parts[3] != "mf32") \
                or len(parts) > 4:
            ap.error(f"--cell {spec!r}: expected REMAT,BATCH,CHUNK with "
                     "optional ',mf32' (e.g. full,8,0 or full,8,0,mf32)")
        if parts[0] not in ("dots", "none", "full", "attn"):
            ap.error(f"--cell {spec!r}: REMAT must be one of "
                     "dots/none/full/attn")
        try:
            batch, chunk = int(parts[1]), int(parts[2])
        except ValueError:
            ap.error(f"--cell {spec!r}: BATCH and CHUNK must be integers")
        cell_specs.append((parts[0], batch, chunk, len(parts) > 3))

    # same pre-probe rule for --model: importing CONFIGS imports jax but
    # initializes no backend, so a typo still fails in milliseconds
    from torchft_tpu.models.llama import CONFIGS

    if args.model not in CONFIGS:
        ap.error(f"--model {args.model!r}: not in CONFIGS "
                 f"({', '.join(sorted(CONFIGS))})")

    # share one persistent compilation cache with every child: a re-run of
    # the sweep (or the bench after it) replays cached executables instead
    # of re-risking tunnel-wedging compiles. Sets JAX_COMPILATION_CACHE_DIR
    # in os.environ, which run_config's children inherit. After argparse:
    # --help must not pay a backend probe.
    from torchft_tpu.utils import enable_compilation_cache, probe_backend

    enable_compilation_cache()

    # probe in a SUBPROCESS: the parent must not hold the TPU runtime open
    # while its children compile against the same tunnelled chip
    status, detail = probe_backend(90.0)
    if status != "accel":
        sys.exit(f"mfu_sweep needs a TPU (probe: {status} {detail}); the "
                 "bench_350m config would grind for hours on CPU (use "
                 "bench.py, which falls back to tiny).")

    cfg, seq = args.model, args.seq
    if args.unroll:
        # children inherit os.environ through run_config
        os.environ["TORCHFT_TPU_SCAN_UNROLL"] = str(args.unroll)

    def _unroll_tag() -> str:
        # model/seq/unroll are run-scoped, not cell-scoped — they must
        # still be in every label or archived sweep lines from different
        # runs are indistinguishable
        tag = f" unroll={args.unroll}" if args.unroll else ""
        if args.model != "bench_350m":
            tag += f" model={args.model}"
        return tag
    attn = os.environ.get("TORCHFT_TPU_ATTENTION", "auto")

    if cell_specs:
        cells = [
            (f"attn={attn} remat={remat:5s} batch={batch:3d} "
             f"chunk={chunk:4d} seq={seq}"
             + (" master=f32" if mf32 else "") + _unroll_tag(),
             {},
             dict(cfg=cfg, batch=batch, seq=seq, remat=remat,
                  chunk=chunk, master_f32=mf32))
            for remat, batch, chunk, mf32 in cell_specs
        ]
        sweep(cells, args.timeout)
        return

    if args.blocks:
        # uniform tiles first (the headline dimension), then asymmetric
        # q/kv combos around the measured uniform winner (1024): a smaller
        # kv tile relieves VMEM pressure, a larger q tile amortizes the
        # online-softmax bookkeeping. Tiles that don't divide --seq are
        # filtered here — failing them in a child would burn a subprocess
        # on a result knowable in the parent.
        combos = [(blk, blk) for blk in (128, 256, 512, 1024, 2048)]
        combos += [(1024, 512), (1024, 256), (512, 1024), (2048, 512),
                   (2048, 1024)]
        dropped = [(bq, bkv) for bq, bkv in combos
                   if seq % bq != 0 or seq % bkv != 0]
        combos = [(bq, bkv) for bq, bkv in combos
                  if seq % bq == 0 and seq % bkv == 0]
        if dropped:
            print(f"# dropped {len(dropped)} tile combos that don't divide "
                  f"seq={seq}: {dropped}", flush=True)
        if not combos:
            sys.exit(f"--blocks: no tile in the ladder divides seq={seq} "
                     "(tiles are multiples of 128)")
        cells = [
            (f"attn=splash block_q={bq:4d} block_kv={bkv:4d} remat=full "
             f"batch=8 seq={seq}" + _unroll_tag(),
             {"TORCHFT_TPU_ATTENTION": "splash",
              "TORCHFT_TPU_SPLASH_BLOCK": str(bq),
              "TORCHFT_TPU_SPLASH_BLOCK_KV": str(bkv)},
             dict(cfg=cfg, batch=8, seq=seq, remat="full", chunk=0))
            for bq, bkv in combos
        ]
        sweep(cells, args.timeout)
        return

    remats = ["dots", "none", "full", "attn"]
    if os.environ.get("TORCHFT_TPU_SWEEP_ATTN") != "1":
        remats.remove("attn")
        print("# remat='attn' skipped from the full matrix: it hung the "
              "round-3 toolchain's compiler; round 4's compiles it fine "
              "(0.436 MFU — slower than 'full') but one observed hang earns "
              "an opt-in gate (TORCHFT_TPU_SWEEP_ATTN=1, or --cell attn,8,0)",
              flush=True)
    cells = [
        (f"attn={attn} remat={remat:5s} batch={batch:3d} chunk={chunk:4d} "
         f"seq={seq}" + _unroll_tag(),
         {},
         dict(cfg=cfg, batch=batch, seq=seq, remat=remat, chunk=chunk))
        for remat, batch, chunk in itertools.product(remats, [8, 16, 32], [0, 512])
    ]
    sweep(cells, args.timeout)


if __name__ == "__main__":
    main()
