"""Sweep remat policy x batch size (and splash block size) for the
single-chip Llama bench.

Finds the config that maximizes MFU on the local chip; bench.py's settings
should track the winner. Uses bench.py's `timed_train_step` so the sweep
measures exactly the workload the headline bench reports. Run on TPU
hardware:
    python benchmarks/mfu_sweep.py            # remat x batch x chunk matrix
    python benchmarks/mfu_sweep.py --blocks   # splash block-size sweep

Every config runs in its OWN SUBPROCESS with a wall-clock timeout: a config
that wedges the compiler (observed on this toolchain: remat="attn" with the
splash kernel compiles >25 min and never returns) must cost one timeout, not
the rest of the matrix. After any timeout the parent re-probes the backend
and stops the sweep if the platform plugin itself has wedged — launching
more compiles at a dead tunnel only deepens the wedge.

remat="attn" is additionally skipped on TPU unless TORCHFT_TPU_SWEEP_ATTN=1:
it is a KNOWN compiler-hang on the current toolchain (models/remat.py), and
an opt-in flag beats rediscovering that one 20-minute timeout at a time.
"""

import argparse
import itertools
import os
import subprocess
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

_CHILD = """\
import sys
sys.path.insert(0, {repo!r})
from bench import timed_train_step
from torchft_tpu.models.llama import CONFIGS
from torchft_tpu.ops import attention as _attn
tps, mfu = timed_train_step(CONFIGS[{cfg!r}], {batch}, {seq}, steps=10,
                            remat={remat!r}, loss_chunk={chunk})
print(f"RESULT {{tps:.1f}} {{mfu:.4f}} {{_attn.LAST_DISPATCH}}", flush=True)
"""


def run_config(cfg, batch, seq, remat, chunk, env_extra, timeout_s):
    """Run one sweep cell in a subprocess; returns a one-line verdict."""
    env = dict(os.environ, **env_extra)
    code = _CHILD.format(repo=REPO, cfg=cfg, batch=batch, seq=seq,
                         remat=remat, chunk=chunk)
    try:
        out = subprocess.run([sys.executable, "-c", code], env=env,
                             capture_output=True, text=True, timeout=timeout_s)
    except subprocess.TimeoutExpired:
        return None, f"TIMEOUT >{timeout_s:.0f}s (compiler wedge?)"
    for line in reversed(out.stdout.splitlines()):
        if line.startswith("RESULT "):
            _, tps, mfu, dispatch = line.split()
            return (float(tps), float(mfu), dispatch), None
    tail = (out.stderr.strip() or out.stdout.strip())[-160:]
    return None, f"FAILED rc={out.returncode}: {tail}"


def backend_alive() -> bool:
    from torchft_tpu.utils import probe_backend

    status, _ = probe_backend(90.0)
    return status in ("accel", "cpu")


def sweep(cells, timeout_s):
    """cells: iterable of (label, env_extra, kwargs for run_config)."""
    for label, env_extra, kw in cells:
        result, err = run_config(env_extra=env_extra, timeout_s=timeout_s, **kw)
        if result:
            tps, mfu, dispatch = result
            print(f"{label}: {tps:10.1f} tok/s  MFU={mfu:.4f}  [{dispatch}]",
                  flush=True)
        else:
            print(f"{label}: {err}", flush=True)
            if err.startswith("TIMEOUT") and not backend_alive():
                print("# backend no longer responds after the timeout — "
                      "stopping the sweep (wedged platform plugin)", flush=True)
                return


def main():
    import jax

    if jax.default_backend() == "cpu":
        sys.exit("mfu_sweep needs a TPU; the bench_350m config would grind "
                 "for hours on CPU (use bench.py, which falls back to tiny).")

    ap = argparse.ArgumentParser()
    ap.add_argument("--blocks", action="store_true",
                    help="sweep splash block sizes instead of the remat matrix")
    ap.add_argument("--timeout", type=float, default=1200.0,
                    help="per-config wall-clock budget (compile + 10 steps)")
    args = ap.parse_args()

    cfg, seq = "bench_350m", 2048
    attn = os.environ.get("TORCHFT_TPU_ATTENTION", "auto")

    if args.blocks:
        cells = [
            (f"attn=splash block={blk:4d} remat=full batch=8",
             {"TORCHFT_TPU_ATTENTION": "splash",
              "TORCHFT_TPU_SPLASH_BLOCK": str(blk)},
             dict(cfg=cfg, batch=8, seq=seq, remat="full", chunk=0))
            for blk in (128, 256, 512, 1024, 2048)
        ]
        sweep(cells, args.timeout)
        return

    remats = ["dots", "none", "full", "attn"]
    if os.environ.get("TORCHFT_TPU_SWEEP_ATTN") != "1":
        remats.remove("attn")
        print("# remat='attn' skipped: known compiler hang on this toolchain "
              "(set TORCHFT_TPU_SWEEP_ATTN=1 to retry)", flush=True)
    cells = [
        (f"attn={attn} remat={remat:5s} batch={batch:3d} chunk={chunk:4d}",
         {},
         dict(cfg=cfg, batch=batch, seq=seq, remat=remat, chunk=chunk))
        for remat, batch, chunk in itertools.product(remats, [8, 16, 32], [0, 512])
    ]
    sweep(cells, args.timeout)


if __name__ == "__main__":
    main()
