"""Sweep remat policy x batch size for the single-chip Llama bench.

Finds the config that maximizes MFU on the local chip; bench.py's settings
should track the winner. Uses bench.py's `timed_train_step` so the sweep
measures exactly the workload the headline bench reports. Run on TPU
hardware:
    python benchmarks/mfu_sweep.py
"""

import itertools
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from bench import timed_train_step  # noqa: E402
from torchft_tpu.models.llama import CONFIGS  # noqa: E402


def main():
    import jax

    if jax.default_backend() == "cpu":
        sys.exit("mfu_sweep needs a TPU; the bench_350m config would grind "
                 "for hours on CPU (use bench.py, which falls back to tiny).")
    cfg = CONFIGS["bench_350m"]
    seq = 2048
    attn = os.environ.get("TORCHFT_TPU_ATTENTION", "auto")
    for remat_mode, batch, chunk in itertools.product(
        ["dots", "none", "full", "attn"], [8, 16, 32], [0, 512]
    ):
        try:
            tps, mfu = timed_train_step(cfg, batch, seq, steps=10,
                                        remat=remat_mode, loss_chunk=chunk)
            print(f"attn={attn} remat={remat_mode:5s} batch={batch:3d} "
                  f"chunk={chunk:4d}: {tps:10.1f} tok/s  MFU={mfu:.4f}",
                  flush=True)
        except Exception as e:
            print(f"attn={attn} remat={remat_mode:5s} batch={batch:3d} "
                  f"chunk={chunk:4d}: FAILED "
                  f"{type(e).__name__}: {str(e)[:120]}", flush=True)


if __name__ == "__main__":
    main()
