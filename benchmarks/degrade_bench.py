"""Degrade-plane benchmark: in-place TP shrink after an intra-group chip
death vs the classic leave-heal-rejoin cycle. Prints ONE JSON line; full
runs also write ``BENCH_DEGRADE.json``.

    python benchmarks/degrade_bench.py [--smoke]

Both legs run REAL managed fleets on this host (lighthouse + Managers +
the host data plane over loopback HTTP/TCP) at the same state size, so
the ratio compares like with like:

- **classic**: recovery_bench's kill scenario — one of two replicas dies
  mid-run, restarts, and heals the FULL state from the surviving peer
  over the HTTP checkpoint transport. The comparator is ``rejoin_s``
  (dead replica's Manager construction -> first commit: quorum rejoin +
  full-state heal), i.e. how long the replica is out of the training
  loop.
- **in_place**: a two-replica fleet where replica 0 declares a k-chip
  group degree; one chip is killed mid-run via the fault injector. The
  manager stages the degrade and commits it at the next safe point: the
  registered reshard hook fetches ONLY the dead chip's shard (state/k
  bytes) over a real loopback ShardStore GET — the gather-free path the
  erasure/heal transport provides — and remaps the param tree onto k-1
  chips (parallel/degrade.reshard_from_survivors), asserting the
  shrunken layout reassembles bitwise-identical. The comparator is the
  manager's ``degraded_reshard_s`` — the latency the degrade ADDS to the
  one re-planned slow step (fetch + reshard + verify). The replica never
  leaves the loop: unlike the classic leg, the step containing the
  reshard still commits, so the steady step it rides is not downtime and
  is not double-counted (the raw kill -> degraded-commit wall clock,
  which does include that step, is recorded as
  ``in_place_commit_window_s`` for reference). The quorum never shrinks
  (asserted).

Provenance caveat (read before quoting): the dead chip's shard is staged
to the loopback store at the kill point (standing in for the redundancy
plane's per-commit staging, whose steady cost is measured separately by
``bench.py --recovery``); staging cost is NOT in the timed window, the
shard fetch over real HTTP IS. Loopback wire for both legs; ratios are
the claim, absolute seconds are this host's.
"""

import argparse
import json
import os
import sys
import threading
import time
from concurrent.futures import ThreadPoolExecutor

REPO_ROOT = os.path.join(os.path.dirname(os.path.abspath(__file__)), "..")
sys.path.insert(0, REPO_ROOT)
sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))

import numpy as np  # noqa: E402

FULL_SIZES_MB = (16, 64, 128)
SMOKE_SIZES_MB = (8,)


def classic_point(size_mb: int, steps: int, kill_at: int) -> dict:
    """Leave-heal-rejoin at one state size: recovery_bench's real kill +
    restart + full-state heal scenario on the host plane."""
    from recovery_bench import run as recovery_run

    r = recovery_run(
        size_mb=size_mb, steps=steps, kill_at=kill_at, plane="host",
        transport="http",
    )
    return {
        "size_mb": size_mb,
        "classic_rejoin_s": r["rejoin_s"],
        "classic_recovery_s": r["recovery_s"],
        "classic_heal_recv_s": r.get("heal_recv_s"),
        "classic_steady_step_s": r["steady_step_s"],
    }


def in_place_point(
    size_mb: int, steps: int, kill_at: int, degree: int = 4
) -> dict:
    """In-place shrink at one state size: kill chip ``degree-1`` of
    replica 0's group mid-run; the staged degrade commits at the next
    safe point with the lost shard sourced over a real loopback shard
    store. Returns the kill->degraded-commit window plus the engine's
    own reshard stats."""
    from torchft_tpu.coordination import LighthouseServer
    from torchft_tpu.manager import Manager
    from torchft_tpu.parallel.degrade import (
        assemble,
        reshard_from_survivors,
        split_even,
    )
    from torchft_tpu.process_group import (
        FakeProcessGroupWrapper,
        ProcessGroupHost,
    )
    from torchft_tpu.redundancy import ShardStore, get_shard, put_shard
    from torchft_tpu.checkpointing.erasure import shard_crc

    n_elem = size_mb * (1 << 20) // 4
    dead_rank = degree - 1
    axes = {"w": 0}

    env_keys = {"TORCHFT_DEGRADE": "on"}
    saved = {k: os.environ.get(k) for k in env_keys}
    os.environ.update(env_keys)

    lh = LighthouseServer(
        bind="127.0.0.1:0", min_replicas=1, join_timeout_ms=2000,
        quorum_tick_ms=20, heartbeat_timeout_ms=3000,
    )
    store = ShardStore("degrade_bench_peer")
    result: dict = {}
    errors: list = []
    # replica 1 watches the quorum across the kill window: the whole
    # point of degrading in place is that membership never changes
    min_participants = [2]

    def replica(rid: int) -> None:
        params = {"w": np.zeros(n_elem, dtype=np.float32)}
        pg = FakeProcessGroupWrapper(ProcessGroupHost(timeout=30.0))
        manager = Manager(
            pg=pg,
            load_state_dict=lambda sd: params.update(
                w=np.asarray(sd["w"], dtype=np.float32)
            ),
            state_dict=lambda: {"w": params["w"]},
            min_replica_size=1,
            use_async_quorum=True,
            replica_id=f"degrade_bench_{rid}",
            lighthouse_addr=f"127.0.0.1:{lh.port}",
            timeout=30.0,
            quorum_timeout=15.0,
        )
        killed_at = [0.0]
        if rid == 0:
            manager.set_group_degree(degree)

            def reshard(dead: int, new_degree: int):
                # survivors' shards are resident slices of the live
                # params; the dead chip's shard comes off the wire
                shards = split_even(params["w"], degree, 0)
                lost_ref = shards[dead]
                fetched = np.frombuffer(
                    get_shard(
                        store.url, "degrade_bench_0", kill_at, dead,
                        lost_ref.nbytes, shard_crc(lost_ref.tobytes()),
                        timeout=300.0,
                    ),
                    dtype=np.float32,
                )
                rank_trees = [
                    None if r == dead else {"w": shards[r]}
                    for r in range(degree)
                ]
                trees, stats = reshard_from_survivors(
                    rank_trees, dead, axes,
                    shard_source=lambda path: fetched,
                )
                back = assemble(trees, axes)
                if not np.array_equal(back["w"], params["w"]):
                    raise RuntimeError(
                        "in-place reshard is not bitwise-equal"
                    )
                result["reshard_stats"] = stats.to_json()
                return stats

            manager.set_reshard_fn(reshard)
        grads = {"w": np.full(n_elem, 0.01, dtype=np.float32)}
        try:
            while manager.current_step() < steps:
                manager.start_quorum()
                avg = manager.allreduce(grads).get_future().wait(120)
                if manager.should_commit():
                    params["w"] = params["w"] - np.asarray(avg["w"])
                    step = manager.current_step()
                    if rid == 1:
                        min_participants[0] = min(
                            min_participants[0], manager.num_participants()
                        )
                    if rid == 0 and step == kill_at:
                        # stage the chip's shard (the redundancy plane's
                        # job, costed by bench.py --recovery) then kill it
                        body = np.ascontiguousarray(
                            split_even(params["w"], degree, 0)[dead_rank]
                        ).tobytes()
                        put_shard(
                            store.url, "degrade_bench_0", kill_at,
                            dead_rank, body, timeout=300.0,
                        )
                        killed_at[0] = time.perf_counter()
                        pg.inject_group_member_death(dead_rank)
                    if (
                        rid == 0
                        and killed_at[0]
                        and "in_place_s" not in result
                        and manager.timings().get("degrade_events", 0) >= 1
                    ):
                        result["in_place_s"] = (
                            time.perf_counter() - killed_at[0]
                        )
                        result["degraded_reshard_s"] = manager.timings()[
                            "degraded_reshard_s"
                        ]
                        result["group_degree_after"] = manager.group_degree
            if rid == 0:
                result["degrade_events"] = manager.timings().get(
                    "degrade_events"
                )
        except Exception as e:  # noqa: BLE001
            errors.append(e)
        finally:
            manager.shutdown(wait=False)

    try:
        with ThreadPoolExecutor(max_workers=2) as ex:
            futs = [ex.submit(replica, r) for r in range(2)]
            for f in futs:
                f.result(timeout=600)
    finally:
        store.shutdown()
        lh.shutdown()
        for k, v in saved.items():
            if v is None:
                os.environ.pop(k, None)
            else:
                os.environ[k] = v
    if errors:
        raise errors[0]
    if "in_place_s" not in result:
        raise RuntimeError("degrade never committed within the run")
    if result.get("degrade_events") != 1:
        raise RuntimeError(
            f"expected exactly one degrade event, saw "
            f"{result.get('degrade_events')}"
        )
    if min_participants[0] != 2:
        raise RuntimeError(
            f"quorum shrank to {min_participants[0]} during the in-place "
            "degrade — the replica left instead of shrinking"
        )
    return {
        "size_mb": size_mb,
        "degree": degree,
        "in_place_reshard_s": round(result["degraded_reshard_s"], 3),
        "in_place_commit_window_s": round(result["in_place_s"], 3),
        "group_degree_after": result["group_degree_after"],
        "quorum_never_shrank": True,
        **{f"reshard_{k}": v for k, v in result["reshard_stats"].items()},
    }


def run(smoke: bool) -> dict:
    sizes = SMOKE_SIZES_MB if smoke else FULL_SIZES_MB
    steps, kill_at = (6, 2) if smoke else (10, 3)
    curve = []
    for s in sizes:
        ip = in_place_point(s, steps=steps, kill_at=kill_at)
        cl = classic_point(s, steps=steps, kill_at=kill_at)
        curve.append(
            {
                **cl,
                **ip,
                "speedup_x": round(
                    cl["classic_rejoin_s"] / ip["in_place_reshard_s"], 2
                ),
            }
        )
    at_max = curve[-1]
    return {
        "degrade_curve": curve,
        "degrade_size_mb_at_max": at_max["size_mb"],
        "degrade_in_place_s_at_max": at_max["in_place_reshard_s"],
        "degrade_commit_window_s_at_max": at_max["in_place_commit_window_s"],
        "degrade_classic_rejoin_s_at_max": at_max["classic_rejoin_s"],
        "degrade_speedup_x": at_max["speedup_x"],
        "degrade_quorum_never_shrank": all(
            p["quorum_never_shrank"] for p in curve
        ),
        "degrade_bitwise_ok": True,  # reshard hook raises otherwise
        "provenance": (
            "loopback host; classic leg = recovery_bench kill + restart + "
            "full-state HTTP heal (rejoin_s: the replica's whole time out "
            "of the loop), in-place leg = real managed fleet with "
            "TORCHFT_DEGRADE=on, one chip of a 4-chip group killed, lost "
            "shard (state/4) fetched over a real ShardStore GET inside the "
            "timed reshard (degraded_reshard_s: the latency ADDED to the "
            "one re-planned slow step — the replica never stops training, "
            "so the steady step it rides is not counted as downtime; the "
            "raw kill->commit window is in_place_commit_window_s). Shard "
            "staging cost excluded (redundancy plane, bench.py "
            "--recovery). Ratios are the claim."
        ),
    }


def main(argv=None) -> None:
    parser = argparse.ArgumentParser(description=__doc__)
    parser.add_argument("--smoke", action="store_true")
    parser.add_argument(
        "--out",
        default=os.path.join(REPO_ROOT, "BENCH_DEGRADE.json"),
        help="degrade-curve output path (full runs only; '-' disables)",
    )
    args = parser.parse_args(argv)

    result = run(smoke=args.smoke)
    if not args.smoke and args.out != "-":
        with open(args.out, "w") as f:
            json.dump(
                {
                    "bench": "degrade plane (in-place TP shrink vs "
                    "leave-heal-rejoin)",
                    "harness": "benchmarks/degrade_bench.py",
                    **result,
                },
                f,
                indent=1,
                sort_keys=True,
            )
            f.write("\n")
        print(f"[degrade_bench] wrote {args.out}", file=sys.stderr)

    print(json.dumps({
        "metric": "in-place degrade speedup over leave-heal-rejoin",
        "value": result["degrade_speedup_x"],
        "unit": "x",
        "vs_baseline": result["degrade_speedup_x"],
        **result,
    }))


if __name__ == "__main__":
    main()
