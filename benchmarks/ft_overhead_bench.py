"""Steady-state fault-tolerance overhead on the real example trainer.

The framework's pitch is fault tolerance at ~zero steady-state cost; this
harness measures that number instead of asserting it. It runs the SAME
trainer the shipped example trains (examples/train_ddp.py ``build_trainer``:
tiny CNN, sgd+momentum, jitted value_and_grad) two ways:

- **bare**: the plain training loop — forward/backward + update, no
  fault-tolerance machinery at all;
- **managed**: the example's actual FT loop — per-step ``start_quorum``
  (async, overlapped with the forward pass), managed allreduce of the grad
  pytree, and a real two-phase ``should_commit`` vote against a live
  lighthouse + manager server.

``ft_overhead_pct`` is the relative per-step cost of the managed loop, and
the per-phase splits (``allreduce_s``, ``should_commit_rpc_s``,
``bookkeeping_s``) from ``Manager.timings()`` say where the paid time went.
Medians throughout: the 1-vCPU bench hosts have scheduler noise that a mean
would launder into the answer.

    python benchmarks/ft_overhead_bench.py

Prints one JSON line; ``bench.py --ft-overhead`` runs it in a CPU-pinned
subprocess and merges the row into the bench artifact, and
``bench.py --ft-overhead --smoke`` is the fast-tier CI gate
(tests/test_bench_smoke.py).
"""

import json
import os
import statistics
import sys
import time

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))
sys.path.insert(0, os.path.join(os.path.dirname(__file__), "..", "examples"))

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _median(xs):
    return statistics.median(xs) if xs else 0.0


def run(steps: int = 30, warmup: int = 5, batch_size: int = 8) -> dict:
    """Time the example trainer bare vs. under a live Manager.

    Returns ``ft_overhead_pct`` (managed vs bare median step), the raw
    medians, and the per-phase steady-state splits from
    ``Manager.timings()``.
    """
    import jax
    import optax

    from train_ddp import build_trainer

    from torchft_tpu.coordination import LighthouseServer
    from torchft_tpu.manager import Manager
    from torchft_tpu.observability import log_timing_event
    from torchft_tpu.process_group import ProcessGroupHost

    total = warmup + steps

    def apply_update(state, optimizer, grads):
        updates, new_opt_state = optimizer.update(
            grads, state["opt_state"], state["params"]
        )
        state["params"] = optax.apply_updates(state["params"], updates)
        state["opt_state"] = new_opt_state

    # -- bare loop ---------------------------------------------------------
    state, grad_fn, optimizer, make_batch = build_trainer(0, batch_size)
    bare_times = []
    for _ in range(total):
        x, y = make_batch()
        t0 = time.perf_counter()
        loss, grads = grad_fn(state["params"], x, y)
        apply_update(state, optimizer, grads)
        float(loss)  # host value fetch = true execution barrier
        bare_times.append(time.perf_counter() - t0)
    bare_step_s = _median(bare_times[warmup:])

    # -- managed loop: real lighthouse, real per-step vote -----------------
    state, grad_fn, optimizer, make_batch = build_trainer(0, batch_size)
    lh = LighthouseServer(
        bind="127.0.0.1:0", min_replicas=1, join_timeout_ms=200,
        quorum_tick_ms=20, heartbeat_timeout_ms=2000,
    )
    manager = Manager(
        pg=ProcessGroupHost(timeout=30.0),
        load_state_dict=lambda sd: None,
        state_dict=lambda: {"params": state["params"]},
        min_replica_size=1,
        replica_id="ft_overhead",
        lighthouse_addr=f"127.0.0.1:{lh.port}",
        timeout=30.0,
    )
    ft_times = []
    splits = {
        "allreduce_s": [],
        "should_commit_rpc_s": [],
        "bookkeeping_s": [],
        # streamed-pipeline stage splits (see Manager._record_pipeline_timings)
        "allreduce_wire_s": [],
        "overlap_efficiency": [],
        "allreduce_buckets": [],
    }
    committed = 0
    try:
        for i in range(total):
            x, y = make_batch()
            t0 = time.perf_counter()
            manager.start_quorum()
            loss, grads = grad_fn(state["params"], x, y)
            reduced = manager.allreduce(grads).get_future().wait(timeout=60)
            if manager.should_commit():
                apply_update(state, optimizer, reduced)
                committed += 1
            float(loss)
            ft_times.append(time.perf_counter() - t0)
            if i >= warmup:
                t = manager.timings()
                for k in splits:
                    if t.get(k) is not None:
                        splits[k].append(t[k])
    finally:
        manager.shutdown(wait=False)
        lh.shutdown()
    ft_step_s = _median(ft_times[warmup:])

    result = {
        "ft_overhead_pct": round(
            (ft_step_s - bare_step_s) / bare_step_s * 100.0, 2
        )
        if bare_step_s > 0
        else None,
        "bare_step_s": round(bare_step_s, 6),
        "ft_step_s": round(ft_step_s, 6),
        "allreduce_s": round(_median(splits["allreduce_s"]), 6),
        "should_commit_rpc_s": round(_median(splits["should_commit_rpc_s"]), 6),
        "bookkeeping_s": round(_median(splits["bookkeeping_s"]), 6),
        "allreduce_wire_s": round(_median(splits["allreduce_wire_s"]), 6),
        "overlap_efficiency": round(_median(splits["overlap_efficiency"]), 4),
        "allreduce_buckets": _median(splits["allreduce_buckets"]),
        "steps": steps,
        "committed": committed,
        "batch_size": batch_size,
    }
    # the same row rides the observability stream so fleet tooling sees the
    # measured overhead next to the per-phase timing snapshots
    log_timing_event(phase="ft_overhead", replica_id="ft_overhead", **result)
    return result


if __name__ == "__main__":
    print(json.dumps(run()))
