#!/bin/bash
# Watch the axon TPU tunnel; the moment a backend probe succeeds, run
# bench.py once (warms .jax_cache so the driver's round-end artifact run
# replays without compiling) and record the result, then exit.
# Probes are kill-safe subprocesses (probe_backend's own timeout) — no
# remote compile is ever interrupted from here.
cd /root/repo
LOG=${1:-/tmp/tunnel_watch_r5.log}
OUT=${2:-/tmp/bench_r5_tpu.log}
for i in $(seq 1 200); do
  STATUS=$(python - <<'EOF'
import sys
sys.path.insert(0, "/root/repo")
from torchft_tpu.utils import probe_backend
status, detail = probe_backend(120.0)
print(status)
EOF
)
  echo "$(date +%H:%M:%S) probe=$STATUS" >> "$LOG"
  if [ "$STATUS" = "accel" ]; then
    echo "$(date +%H:%M:%S) tunnel healthy; running bench.py" >> "$LOG"
    python bench.py > "$OUT" 2>&1
    echo "$(date +%H:%M:%S) bench rc=$? (see $OUT)" >> "$LOG"
    exit 0
  fi
  sleep 600
done
echo "$(date +%H:%M:%S) gave up" >> "$LOG"
