#!/bin/bash
# Watch the axon TPU tunnel; the moment a backend probe succeeds, run
# bench.py once (warms .jax_cache so the driver's round-end artifact run
# replays without compiling) and record the result, then exit.
# Probes are kill-safe subprocesses (probe_backend's own timeout) — no
# remote compile is ever interrupted from here.
cd /root/repo
LOG=${1:-/tmp/tunnel_watch_r5.log}
OUT=${2:-/tmp/bench_r5_tpu.log}
for i in $(seq 1 200); do
  STATUS=$(python - <<'EOF'
import sys
sys.path.insert(0, "/root/repo")
from torchft_tpu.utils import probe_backend
status, detail = probe_backend(120.0)
print(status)
EOF
)
  echo "$(date +%H:%M:%S) probe=$STATUS" >> "$LOG"
  if [ "$STATUS" = "accel" ]; then
    echo "$(date +%H:%M:%S) tunnel healthy; running bench.py" >> "$LOG"
    python bench.py > "$OUT" 2>&1
    RC=$?
    echo "$(date +%H:%M:%S) bench rc=$RC (see $OUT)" >> "$LOG"
    if [ "$RC" = "0" ]; then
      python - "$OUT" >> "$LOG" 2>&1 <<'EOF'
import json, sys
from datetime import datetime, timezone
lines = [l for l in open(sys.argv[1]) if l.startswith('{"metric"')]
if not lines:
    print("BENCH_SELF: no metric line in bench output; nothing saved")
    raise SystemExit(0)
rec = json.loads(lines[-1])
if "error" in rec:
    print(f"BENCH_SELF: bench fell back ({rec['error']}); nothing saved")
    raise SystemExit(0)
rec["provenance"] = (
    "self-recorded by benchmarks/tunnel_watch.sh on the first healthy "
    "probe after the round-4 wedge; bench.py finished at "
    + datetime.now(timezone.utc).strftime("%Y-%m-%dT%H:%MZ")
    + " (see the probe timeline in the watcher log). The persistent "
    "compilation cache (.jax_cache/) was enabled for the run; whether "
    "it replayed or compiled fresh depends on the toolchain matching "
    "the cache's. If BENCH_r05.json shows a TPU number, prefer it."
)
json.dump(rec, open("/root/repo/BENCH_SELF_r05.json", "w"), indent=1)
print("BENCH_SELF: saved BENCH_SELF_r05.json")
EOF
    fi
    exit 0
  fi
  sleep 600
done
echo "$(date +%H:%M:%S) gave up" >> "$LOG"
