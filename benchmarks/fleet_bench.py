"""Fleet-scale control-plane benchmark: flat vs two-level lighthouse.

Runs the :mod:`torchft_tpu._test.fleet_sim` harness over a grid of fleet
sizes x topologies and emits one JSON line (last line of stdout) with the
scaling curve, plus ``BENCH_FLEET.json`` on full runs:

    python benchmarks/fleet_bench.py           # full: 100/500/1000, both
    python benchmarks/fleet_bench.py --smoke   # tier-1 gate: 40 replicas

The headline numbers the two-level tier must defend (asserted by
``bench.py --fleet``):

- root heartbeat fan-in bytes per fleet-wide beat interval drops >= 5x at
  the largest size (>= 2x in smoke, which is too small for the full win);
- two-level quorum-convergence latency stays flat (within 2x) from the
  smallest to the largest size, with both sides floored at one root
  quorum tick — sub-tick latencies are scheduling noise, not a trend.

Everything runs on loopback against the real native servers; fake replicas
drive the real wire protocol (see fleet_sim's module docstring for the
phase breakdown). Churn is exercised at every point: a slice of the fleet
dies mid-run, fresh replicas enroll, and the next quorum round must still
converge — its latency is reported but not gated here (it is dominated by
the configured heartbeat expiry, which the chaos-soak test covers).
"""

from __future__ import annotations

import argparse
import json
import math
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from torchft_tpu._test.fleet_sim import FleetConfig, run_fleet  # noqa: E402

REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

FULL_SIZES = (100, 500, 1000)
SMOKE_SIZE = 40


def _point_config(n: int, topology: str, smoke: bool) -> FleetConfig:
    if smoke:
        return FleetConfig(
            n_replicas=n,
            topology=topology,
            n_aggregators=2 if topology == "two_level" else 0,
            beat_interval_s=0.3,
            step_interval_s=3.0,
            measure_s=3.0,
            agg_tick_ms=100,
            heartbeat_timeout_ms=2000,
            quorum_tick_ms=50,
            join_timeout_ms=15000,
            scrape_iters=10,
            churn_replicas=4,
        )
    return FleetConfig(
        n_replicas=n,
        topology=topology,
        # ~1 aggregator per 64 replicas (the operations-guide rule of thumb).
        n_aggregators=max(1, math.ceil(n / 64)) if topology == "two_level" else 0,
        beat_interval_s=1.0,
        step_interval_s=15.0,
        measure_s=8.0,
        agg_tick_ms=500,
        # Generous on a saturated 1-vCPU box: a beat round for 1000 replicas
        # can stretch well past the interval, and a false death would turn
        # the fan-in window into a churn measurement.
        heartbeat_timeout_ms=8000,
        quorum_tick_ms=100,
        join_timeout_ms=30000,
        scrape_iters=25,
        churn_replicas=max(2, n // 100),
    )


def run_grid(sizes, smoke: bool) -> dict:
    points = []
    for n in sizes:
        for topology in ("flat", "two_level"):
            cfg = _point_config(n, topology, smoke)
            print(
                f"[fleet_bench] {topology} n={n} "
                f"(aggs={cfg.n_aggregators or 0})...",
                file=sys.stderr,
            )
            points.append(run_fleet(cfg))

    def _pt(n, topology):
        for p in points:
            if p["n_replicas"] == n and p["topology"] == topology:
                return p
        raise KeyError((n, topology))

    n_max, n_min = max(sizes), min(sizes)
    flat_max = _pt(n_max, "flat")
    two_max = _pt(n_max, "two_level")
    two_min = _pt(n_min, "two_level")
    fanin_ratio = flat_max["root_fanin_bytes_per_tick"] / max(
        two_max["root_fanin_bytes_per_tick"], 1.0
    )
    # The root evaluates pending quorums on a quorum_tick_ms cadence, so any
    # convergence under one tick is scheduling noise, not a trend — floor
    # both sides at one tick before taking the ratio (8ms vs 44ms are both
    # "instant" next to a 50ms tick; a real regression to hundreds of ms
    # still blows through the 2x gate).
    tick_ms = float(two_max.get("quorum_tick_ms", 50))
    latency_ratio = max(two_max["quorum_convergence_ms"], tick_ms) / max(
        two_min["quorum_convergence_ms"], tick_ms
    )
    summary = {
        "fleet_sizes": list(sizes),
        "fleet_fanin_ratio_at_max": fanin_ratio,
        "fleet_flat_fanin_bytes_per_tick_at_max": flat_max[
            "root_fanin_bytes_per_tick"
        ],
        "fleet_two_level_fanin_bytes_per_tick_at_max": two_max[
            "root_fanin_bytes_per_tick"
        ],
        "fleet_two_level_latency_scaling": latency_ratio,
        "fleet_two_level_convergence_ms_at_max": two_max[
            "quorum_convergence_ms"
        ],
        "fleet_flat_convergence_ms_at_max": flat_max["quorum_convergence_ms"],
        "fleet_two_level_delivery_ms_at_max": two_max.get(
            "quorum_delivery_ms", 0.0
        ),
        "fleet_flat_delivery_ms_at_max": flat_max.get(
            "quorum_delivery_ms", 0.0
        ),
        "fleet_all_converged": all(
            p["quorum_converged"]
            and (p.get("churn_converged", True) is not False)
            for p in points
        ),
    }
    return {"points": points, "summary": summary}


def main(argv=None) -> None:
    parser = argparse.ArgumentParser(description=__doc__)
    parser.add_argument("--smoke", action="store_true")
    parser.add_argument(
        "--sizes", default="", help="comma-separated fleet sizes override"
    )
    parser.add_argument(
        "--out",
        default=os.path.join(REPO_ROOT, "BENCH_FLEET.json"),
        help="scaling-curve output path (full runs only; '-' disables)",
    )
    args = parser.parse_args(argv)

    if args.sizes:
        sizes = tuple(int(s) for s in args.sizes.split(","))
    else:
        sizes = (SMOKE_SIZE,) if args.smoke else FULL_SIZES

    result = run_grid(sizes, smoke=args.smoke)
    if not args.smoke and args.out != "-":
        with open(args.out, "w") as f:
            json.dump(
                {
                    "bench": "fleet control plane (flat vs two-level)",
                    "harness": "torchft_tpu/_test/fleet_sim.py",
                    **result,
                },
                f,
                indent=1,
                sort_keys=True,
            )
            f.write("\n")
        print(f"[fleet_bench] wrote {args.out}", file=sys.stderr)

    print(json.dumps({
        "metric": "fleet fan-in reduction (flat / two-level, largest size)",
        "value": result["summary"]["fleet_fanin_ratio_at_max"],
        "unit": "x",
        "vs_baseline": result["summary"]["fleet_fanin_ratio_at_max"],
        **result["summary"],
    }))


if __name__ == "__main__":
    main()
