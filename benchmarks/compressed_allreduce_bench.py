"""Compressed vs uncompressed streamed managed allreduce (host loopback).

PR 6's compressed streaming collectives claim the bucketed pipeline's wire
stage gets ≥2× effective bandwidth once buckets ride the ring fp8/int8-
compressed (1 code byte + 4/512 scale bytes per element instead of 4 f32
bytes, at the price of per-hop dequantize→accumulate→requantize compute
and pack-side codec cost absorbed by the pipeline's pack stage). This
harness measures that claim: two replica groups exchange the SAME
multi-bucket gradient tree through real Managers (live lighthouse,
per-step quorum + two-phase vote, loopback ProcessGroupHost) once per
compress mode — ``off`` (the bit-identical default), ``fp8``, ``int8`` —
and reports each mode's median step wall, pipeline stage splits
(``pack_s`` / ``wire_s`` / ``unpack_s`` from ``Manager.timings()``),
``overlap_efficiency``, the bytes each mode actually framed onto the
link (``wire_mb_per_step``), and the EFFECTIVE wire bandwidth: logical
(uncompressed f32) gradient bytes divided by the send-side wire
occupancy — seconds the transport spent inside sendall pushing frames
(``ProcessGroupHost.wire_stats``), NOT the manager's dispatch-to-done
``wire_s`` spans, which also count bucket queueing and (on small hosts)
codec CPU contention. The quotient reads directly as "bytes of gradient
delivered per second the wire was busy". ``bandwidth_ratio_fp8`` /
``bandwidth_ratio_int8`` are each mode's effective bandwidth over
``off``'s.

Medians throughout, same policy as the other harnesses.

    python benchmarks/compressed_allreduce_bench.py [--size-mb 64] [--cap-mb 4]

Prints one JSON line; ``bench.py --compressed-allreduce`` runs it in a
CPU-pinned subprocess (the committed BENCH_COMPRESS.json numbers) and
``--compressed-allreduce --smoke`` is the fast-tier CI gate
(tests/test_bench_smoke.py) asserting the per-mode split keys.
"""

import argparse
import json
import os
import statistics
import sys
import threading
import time

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))

import numpy as np  # noqa: E402

MODES = ("off", "fp8", "int8")


def _median(xs):
    return statistics.median(xs) if xs else 0.0


def _make_tree(size_mb: float, leaves: int) -> dict:
    n_total = int(size_mb * (1 << 20)) // 4
    per = max(1, n_total // leaves)
    rng = np.random.RandomState(0)
    return {
        f"w{i}": rng.randn(per).astype(np.float32) for i in range(leaves)
    }


def _run_mode(mode: str, tree: dict, cap_bytes: int, steps: int,
              warmup: int) -> dict:
    from torchft_tpu.coordination import LighthouseServer
    from torchft_tpu.manager import Manager
    from torchft_tpu.process_group import ProcessGroupHost

    lh = LighthouseServer(
        bind="127.0.0.1:0", min_replicas=2, join_timeout_ms=5000,
        quorum_tick_ms=20, heartbeat_timeout_ms=5000,
    )
    barrier = threading.Barrier(2)
    step_times: list = []
    snaps: list = []
    wire_snaps: list = []
    errors: list = []

    def replica(rid: int) -> None:
        manager = None
        pg = ProcessGroupHost(timeout=60.0)
        try:
            manager = Manager(
                pg=pg,
                load_state_dict=lambda sd: None,
                state_dict=lambda: {"x": np.zeros(1, np.float32)},
                min_replica_size=2,
                replica_id=f"compress_{mode}_{rid}",
                lighthouse_addr=f"127.0.0.1:{lh.port}",
                timeout=60.0,
                bucket_cap_bytes=cap_bytes,
                stream_buckets=True,
                compress=mode,
            )
            for i in range(steps):
                barrier.wait(timeout=180)
                t0 = time.perf_counter()
                manager.start_quorum()
                manager.allreduce_streamed(tree).wait(timeout=120)
                if not manager.should_commit():
                    errors.append(f"commit failed rid={rid} step={i}")
                if rid == 0:
                    step_times.append(time.perf_counter() - t0)
                    wire_snaps.append(pg.wire_stats())
                    if i >= warmup:
                        snaps.append(manager.timings())
        except Exception as e:  # noqa: BLE001
            errors.append(f"rid={rid}: {type(e).__name__}: {e}")
            barrier.abort()
        finally:
            if manager is not None:
                manager.shutdown(wait=False)

    threads = [
        threading.Thread(target=replica, args=(rid,), daemon=True)
        for rid in (0, 1)
    ]
    try:
        for t in threads:
            t.start()
        for t in threads:
            t.join(timeout=600)
    finally:
        lh.shutdown()
    if errors:
        raise RuntimeError("; ".join(errors[:3]))

    out = {"step_s": round(_median(step_times[warmup:]), 6)}
    for key, snap_key in (
        ("pack_s", "allreduce_pack_s"),
        ("wire_s", "allreduce_wire_s"),
        ("unpack_s", "allreduce_unpack_s"),
        ("buckets", "allreduce_buckets"),
        ("overlap_efficiency", "overlap_efficiency"),
    ):
        vals = [s[snap_key] for s in snaps if snap_key in s]
        if vals:
            out[key] = round(_median(vals), 6)
    # transport occupancy over the measured (post-warmup) steps: bytes this
    # rank's sender actually framed onto the link, and the seconds sendall
    # spent pushing them (ProcessGroupHost.wire_stats)
    if len(wire_snaps) > warmup:
        first, last = wire_snaps[warmup - 1], wire_snaps[-1]
        measured = len(wire_snaps) - warmup
        out["wire_mb_per_step"] = round(
            (last["bytes_sent"] - first["bytes_sent"])
            / (1 << 20) / measured, 3
        )
        out["wire_busy_s_per_step"] = round(
            (last["busy_s"] - first["busy_s"]) / measured, 6
        )
    return out


def run(
    size_mb: float = 64,
    leaves: int = 16,
    cap_mb: float = 4,
    steps: int = 8,
    warmup: int = 2,
) -> dict:
    """Time the two-replica loopback exchange per compress mode.

    Returns per-mode stage splits + effective wire bandwidth (logical
    uncompressed bytes / wire_s, in MB/s) and the fp8/int8 bandwidth
    ratios over the uncompressed run.
    """
    from torchft_tpu.observability import log_timing_event

    tree = _make_tree(size_mb, leaves)
    logical_mb = sum(v.nbytes for v in tree.values()) / (1 << 20)
    cap_bytes = int(cap_mb * (1 << 20))

    modes = {}
    for mode in MODES:
        m = _run_mode(mode, tree, cap_bytes, steps, warmup)
        # effective wire bandwidth: logical (uncompressed f32) gradient MB
        # delivered per second of send-side wire occupancy. Occupancy, not
        # the manager's dispatch-to-done wire_s spans: the spans also count
        # bucket queueing and (on small hosts) codec CPU contention, which
        # would charge compute time to the wire
        busy = m.get("wire_busy_s_per_step") or 0.0
        m["effective_wire_mb_s"] = (
            round(logical_mb / busy, 3) if busy > 0 else None
        )
        modes[mode] = m

    off_bw = modes["off"]["effective_wire_mb_s"]
    result = {"modes": modes, "size_mb": size_mb, "leaves": leaves,
              "cap_mb": cap_mb, "steps": steps,
              "logical_mb": round(logical_mb, 3)}
    for mode in ("fp8", "int8"):
        bw = modes[mode]["effective_wire_mb_s"]
        result[f"bandwidth_ratio_{mode}"] = (
            round(bw / off_bw, 3) if bw and off_bw else None
        )
        step_off, step_m = modes["off"]["step_s"], modes[mode]["step_s"]
        result[f"step_speedup_pct_{mode}"] = (
            round((step_off - step_m) / step_off * 100.0, 2)
            if step_off > 0 else None
        )
    log_timing_event(phase="compressed_allreduce_bench",
                     replica_id="compress_bench", **{
                         k: v for k, v in result.items() if k != "modes"
                     })
    return result


if __name__ == "__main__":
    p = argparse.ArgumentParser()
    p.add_argument("--size-mb", type=float, default=64)
    p.add_argument("--leaves", type=int, default=16)
    p.add_argument("--cap-mb", type=float, default=4)
    p.add_argument("--steps", type=int, default=8)
    p.add_argument("--warmup", type=int, default=2)
    a = p.parse_args()
    print(json.dumps(run(a.size_mb, a.leaves, a.cap_mb, a.steps, a.warmup)))
