"""Shared CLI surface for the cluster launch paths (GKE + slurm).

One definition of the training/fault-tolerance knobs and of the reference
DiLoCo semi-sync config (torchft/examples/slurm/runner.py:23-60: sync_steps
20, 2 fragments, 1-step delay) so the two runners cannot drift apart.
"""

from __future__ import annotations

import argparse

# the reference semi-sync config — same Llama trainer, DiLoCo mode
DILOCO_TRAINER_FLAGS = [
    "--diloco",
    "--sync-every=20",
    "--num-fragments=2",
    "--fragment-sync-delay=1",
]


def add_training_args(p: argparse.ArgumentParser) -> None:
    """Args shared verbatim by every launch path."""
    p.add_argument("--replica-groups", type=int, default=4)
    p.add_argument("--min-replicas", type=int, default=2)
    p.add_argument("--model-config", default="llama3_8b")
    p.add_argument("--local-batch-size", type=int, default=2)
    p.add_argument("--steps", type=int, default=10000)
    p.add_argument("--semi-sync-method", choices=["none", "diloco"],
                   default="none")
    p.add_argument("--sp", type=int, default=1,
                   help="in-group sequence-parallel degree")
    p.add_argument("--tp", type=int, default=1,
                   help="in-group tensor-parallel degree")


def mesh_args(args: argparse.Namespace, chips: int) -> "tuple[int, int, int]":
    """Resolve the in-group mesh, defaulting fsdp to fill the group's chips
    (the trainer's own default of 1x1x1 would leave all but one chip idle).

    Raises ValueError when fsdp*sp*tp does not cover ``chips``.
    """
    fsdp = args.fsdp if args.fsdp else max(1, chips // (args.sp * args.tp))
    if fsdp * args.sp * args.tp != chips:
        raise ValueError(
            f"mesh fsdp({fsdp})*sp({args.sp})*tp({args.tp}) must equal the "
            f"group's chip count ({chips})"
        )
    return fsdp, args.sp, args.tp
