"""Slurm launch path for the Llama-3-8B FT-HSDP target.

Role-equivalent of the reference's slurm runner
(torchft/examples/slurm/runner.py:23-60): submit one scheduler job per
replica group plus the lighthouse, each carrying the framework's env
contract (torchft_tpu/launcher.py:39-43). TPU clusters are usually GKE
(see gke_runner.py); this covers slurm-managed TPU-VM fleets.

Dry-run friendly: ``--dry-run`` prints the sbatch scripts instead of
submitting, so the launch path is reviewable without a cluster:

    python examples/cluster/slurm_runner.py --replica-groups 4 --dry-run
"""

from __future__ import annotations

import argparse
import os
import subprocess
import sys

sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))
from _common import (  # noqa: E402
    DILOCO_TRAINER_FLAGS,
    add_training_args,
    mesh_args,
)

LIGHTHOUSE_SBATCH = """\
#!/bin/bash
#SBATCH --job-name=torchft-lighthouse
#SBATCH --nodes=1
#SBATCH --nodelist={lighthouse_host}
#SBATCH --output=lighthouse.log
#SBATCH --requeue
exec python -m torchft_tpu.lighthouse \\
    --bind=0.0.0.0:{port} --min-replicas={min_replicas} \\
    --join-timeout-ms=60000 --quorum-tick-ms=100 --heartbeat-timeout-ms=5000
"""

REPLICA_SBATCH = """\
#!/bin/bash
#SBATCH --job-name=torchft-replica-{rid}
#SBATCH --nodes=1
#SBATCH --output=replica_{rid}_%j.log
#SBATCH --requeue
export TORCHFT_LIGHTHOUSE={lighthouse_host}:{port}
export REPLICA_GROUP_ID={rid}
export NUM_REPLICA_GROUPS={num_groups}
export GROUP_RANK=0
export GROUP_WORLD_SIZE=1
exec python {train_script} \\
    {config_arg}--batch-size={local_batch_size} --steps={steps} \\
    --fsdp={fsdp} --sp={sp} --tp={tp}{extra}
"""


def build_scripts(args: argparse.Namespace) -> "list[tuple[str, str]]":
    scripts = [
        (
            "lighthouse.sbatch",
            LIGHTHOUSE_SBATCH.format(
                # pin to the host every replica's TORCHFT_LIGHTHOUSE points
                # at; otherwise slurm may place the lighthouse elsewhere
                lighthouse_host=args.lighthouse_host,
                port=args.port,
                min_replicas=args.min_replicas,
            ),
        )
    ]
    # absolute path: sbatch scripts start in the submission cwd, which is
    # rarely the repo root
    train_script = os.path.abspath(
        os.path.join(os.path.dirname(__file__), "..", "train_llama_hsdp.py")
    )
    config_arg = f"--config={args.model_config} "
    fsdp, sp, tp = mesh_args(args, args.chips_per_node)
    extra = ""
    if args.semi_sync_method == "diloco":
        extra = " \\\n    " + " ".join(DILOCO_TRAINER_FLAGS)
    for rid in range(args.replica_groups):
        scripts.append(
            (
                f"replica_{rid}.sbatch",
                REPLICA_SBATCH.format(
                    rid=rid,
                    lighthouse_host=args.lighthouse_host,
                    port=args.port,
                    num_groups=args.replica_groups,
                    train_script=train_script,
                    config_arg=config_arg,
                    local_batch_size=args.local_batch_size,
                    steps=args.steps,
                    fsdp=fsdp,
                    sp=sp,
                    tp=tp,
                    extra=extra,
                ),
            )
        )
    return scripts


def main(argv: "list[str] | None" = None) -> None:
    p = argparse.ArgumentParser(description=__doc__)
    add_training_args(p)
    p.add_argument(
        "--lighthouse-host", default=None,
        help="hostname running the lighthouse job (REQUIRED to submit: each "
             "sbatch job is its own allocation, so no in-script expansion "
             "can discover the lighthouse's node)",
    )
    p.add_argument("--port", type=int, default=29510)
    p.add_argument("--chips-per-node", type=int, default=4,
                   help="TPU chips per TPU-VM node (the in-group mesh)")
    p.add_argument("--fsdp", type=int, default=0,
                   help="in-group ZeRO shard degree (0 = fill the node)")
    p.add_argument("--dry-run", action="store_true")
    args = p.parse_args(argv)
    if args.lighthouse_host is None:
        if not args.dry_run:
            p.error("--lighthouse-host is required to submit")
        args.lighthouse_host = "LIGHTHOUSE_HOST"  # review placeholder

    for name, text in build_scripts(args):
        if args.dry_run:
            sys.stdout.write(f"# === {name} ===\n{text}\n")
        else:
            with open(name, "w") as f:
                f.write(text)
            subprocess.run(["sbatch", name], check=True)
            print(f"submitted {name}")


if __name__ == "__main__":
    main()
