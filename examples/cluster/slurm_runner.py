"""Slurm launch path for the Llama-3-8B FT-HSDP target.

Role-equivalent of the reference's slurm runner
(torchft/examples/slurm/runner.py:23-60): submit one scheduler job per
replica group plus the lighthouse, each carrying the framework's env
contract (torchft_tpu/launcher.py:39-43). TPU clusters are usually GKE
(see gke_runner.py); this covers slurm-managed TPU-VM fleets.

Dry-run friendly: ``--dry-run`` prints the sbatch scripts instead of
submitting, so the launch path is reviewable without a cluster:

    python examples/cluster/slurm_runner.py --replica-groups 4 --dry-run
"""

from __future__ import annotations

import argparse
import shlex
import subprocess
import sys

LIGHTHOUSE_SBATCH = """\
#!/bin/bash
#SBATCH --job-name=torchft-lighthouse
#SBATCH --nodes=1
#SBATCH --output=lighthouse.log
exec python -m torchft_tpu.lighthouse \\
    --bind=0.0.0.0:{port} --min-replicas={min_replicas} \\
    --join-timeout-ms=60000 --quorum-tick-ms=100 --heartbeat-timeout-ms=5000
"""

REPLICA_SBATCH = """\
#!/bin/bash
#SBATCH --job-name=torchft-replica-{rid}
#SBATCH --nodes=1
#SBATCH --output=replica_{rid}_%j.log
#SBATCH --requeue
export TORCHFT_LIGHTHOUSE={lighthouse_host}:{port}
export REPLICA_GROUP_ID={rid}
export NUM_REPLICA_GROUPS={num_groups}
export GROUP_RANK=0
export GROUP_WORLD_SIZE=1
exec python {train_script} \\
    {config_arg}--batch-size={local_batch_size} --steps={steps}{extra}
"""


def build_scripts(args: argparse.Namespace) -> "list[tuple[str, str]]":
    scripts = [
        (
            "lighthouse.sbatch",
            LIGHTHOUSE_SBATCH.format(
                port=args.port, min_replicas=args.min_replicas
            ),
        )
    ]
    train_script = "examples/train_llama_hsdp.py"
    config_arg = f"--config={args.model_config} "
    extra = ""
    if args.semi_sync_method == "diloco":
        # same Llama trainer, semi-sync mode (reference config)
        extra = (" \\\n    --diloco --sync-every=20 --num-fragments=2"
                 " --fragment-sync-delay=1")
    for rid in range(args.replica_groups):
        scripts.append(
            (
                f"replica_{rid}.sbatch",
                REPLICA_SBATCH.format(
                    rid=rid,
                    lighthouse_host=args.lighthouse_host,
                    port=args.port,
                    num_groups=args.replica_groups,
                    train_script=train_script,
                    config_arg=config_arg,
                    local_batch_size=args.local_batch_size,
                    steps=args.steps,
                    extra=extra,
                ),
            )
        )
    return scripts


def main(argv: "list[str] | None" = None) -> None:
    p = argparse.ArgumentParser(description=__doc__)
    p.add_argument("--replica-groups", type=int, default=4)
    p.add_argument("--min-replicas", type=int, default=2)
    p.add_argument(
        "--lighthouse-host", default=None,
        help="hostname running the lighthouse job (REQUIRED to submit: each "
             "sbatch job is its own allocation, so no in-script expansion "
             "can discover the lighthouse's node)",
    )
    p.add_argument("--port", type=int, default=29510)
    p.add_argument("--model-config", default="llama3_8b")
    p.add_argument("--local-batch-size", type=int, default=2)
    p.add_argument("--steps", type=int, default=10000)
    p.add_argument("--semi-sync-method", choices=["none", "diloco"],
                   default="none")
    p.add_argument("--dry-run", action="store_true")
    args = p.parse_args(argv)
    if args.lighthouse_host is None:
        if not args.dry_run:
            p.error("--lighthouse-host is required to submit")
        args.lighthouse_host = "LIGHTHOUSE_HOST"  # review placeholder

    for name, text in build_scripts(args):
        if args.dry_run:
            sys.stdout.write(f"# === {name} ===\n{text}\n")
        else:
            with open(name, "w") as f:
                f.write(text)
            subprocess.run(["sbatch", name], check=True)
            print(f"submitted {name}")


if __name__ == "__main__":
    main()
