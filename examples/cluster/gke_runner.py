"""GKE launch path for the Llama-3-8B FT-HSDP target.

Role-equivalent of the reference's slurm runner
(torchft/examples/slurm/runner.py:23-60: one scheduler job per replica
group running the Llama-3-8B config with the fault-tolerance env) — but
TPU-native: on Google Cloud, multi-slice TPU training runs on GKE, so the
unit of scheduling is a JobSet of TPU-slice Jobs plus a lighthouse
Deployment, not sbatch scripts.

This generates (and optionally `kubectl apply`s) the manifests:

- 1 lighthouse Deployment + Service (stable DNS name for
  ``TORCHFT_LIGHTHOUSE``)
- N replica-group Jobs, each requesting one TPU slice
  (``google.com/tpu``), running ``examples/train_llama_hsdp.py`` with the
  framework's env contract (REPLICA_GROUP_ID / NUM_REPLICA_GROUPS /
  TORCHFT_LIGHTHOUSE — torchft_tpu/launcher.py:39-43). Jobs restart on
  failure (``backoffLimit``); a restarted group rejoins the quorum and
  live-heals from a peer, so no coordinated restart is needed.

No cluster is required to generate or inspect the manifests:

    python examples/cluster/gke_runner.py --replica-groups 4 \
        --tpu-topology 4x4 --tpu-type tpu-v5p-slice --out jobs.yaml
    kubectl apply -f jobs.yaml   # on a real cluster

Mirrored training config (reference runner.py:23-60): llama3_8b,
local_batch_size 2, steps 10000, optional DiLoCo semi-sync
(sync_every 20, 2 fragments).
"""

from __future__ import annotations

import argparse
import os
import subprocess
import sys

sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))
from _common import (  # noqa: E402
    DILOCO_TRAINER_FLAGS,
    add_training_args,
    mesh_args,
)

LIGHTHOUSE_PORT = 29510

LIGHTHOUSE_MANIFEST = """\
apiVersion: apps/v1
kind: Deployment
metadata:
  name: torchft-lighthouse
  labels: {{app: torchft-lighthouse}}
spec:
  replicas: 1
  selector:
    matchLabels: {{app: torchft-lighthouse}}
  template:
    metadata:
      labels: {{app: torchft-lighthouse}}
    spec:
      containers:
      - name: lighthouse
        image: {image}
        command: ["python", "-m", "torchft_tpu.lighthouse"]
        args:
        - "--bind=0.0.0.0:{port}"
        - "--min-replicas={min_replicas}"
        - "--join-timeout-ms=60000"
        - "--quorum-tick-ms=100"
        - "--heartbeat-timeout-ms=5000"
        ports:
        - containerPort: {port}
---
apiVersion: v1
kind: Service
metadata:
  name: torchft-lighthouse
spec:
  selector: {{app: torchft-lighthouse}}
  ports:
  - port: {port}
    targetPort: {port}
"""

REPLICA_JOB_MANIFEST = """\
apiVersion: batch/v1
kind: Job
metadata:
  name: torchft-replica-{rid}
  labels: {{app: torchft-replica, replica-group: "{rid}"}}
spec:
  # a dead replica group is rescheduled and live-heals from a peer on
  # rejoin; unlimited-ish retries are the FT design, not a hack
  backoffLimit: 1000
  template:
    metadata:
      labels: {{app: torchft-replica, replica-group: "{rid}"}}
    spec:
      restartPolicy: OnFailure
      nodeSelector:
        cloud.google.com/gke-tpu-accelerator: {tpu_type}
        cloud.google.com/gke-tpu-topology: {tpu_topology}
      containers:
      - name: trainer
        image: {image}
        command: ["python", "{train_script}"]
        args:
        - "--batch-size={local_batch_size}"
        - "--steps={steps}"{extra_args}
        env:
        - name: TORCHFT_LIGHTHOUSE
          value: "torchft-lighthouse:{port}"
        - name: REPLICA_GROUP_ID
          value: "{rid}"
        - name: NUM_REPLICA_GROUPS
          value: "{num_groups}"
        - name: GROUP_RANK
          value: "0"
        - name: GROUP_WORLD_SIZE
          value: "1"
        resources:
          requests: {{"google.com/tpu": {chips}}}
          limits: {{"google.com/tpu": {chips}}}
"""


def build_manifests(args: argparse.Namespace) -> str:
    docs = [
        LIGHTHOUSE_MANIFEST.format(
            image=args.image,
            port=LIGHTHOUSE_PORT,
            min_replicas=args.min_replicas,
        )
    ]
    train_script = "examples/train_llama_hsdp.py"
    topo_chips = 1
    for d in args.tpu_topology.split("x"):
        topo_chips *= int(d)
    # chips-per-slice is derived from the topology (GKE only schedules pods
    # whose google.com/tpu request matches the slice); the flag exists only
    # as an explicit override and must then agree
    chips = args.chips_per_slice or topo_chips
    if chips != topo_chips:
        raise ValueError(
            f"--tpu-topology {args.tpu_topology} has {topo_chips} chips but "
            f"--chips-per-slice override is {args.chips_per_slice}"
        )
    fsdp, sp, tp = mesh_args(args, chips)
    extra = '\n        - "--config={0}"'.format(args.model_config)
    extra += (
        f'\n        - "--fsdp={fsdp}"'
        f'\n        - "--sp={sp}"'
        f'\n        - "--tp={tp}"'
    )
    if args.semi_sync_method == "diloco":
        extra += "".join(
            f'\n        - "{flag}"' for flag in DILOCO_TRAINER_FLAGS
        )
    for rid in range(args.replica_groups):
        docs.append(
            REPLICA_JOB_MANIFEST.format(
                rid=rid,
                image=args.image,
                tpu_type=args.tpu_type,
                tpu_topology=args.tpu_topology,
                chips=chips,
                train_script=train_script,
                local_batch_size=args.local_batch_size,
                steps=args.steps,
                num_groups=args.replica_groups,
                port=LIGHTHOUSE_PORT,
                extra_args=extra,
            )
        )
    return "---\n".join(docs)


def main(argv: "list[str] | None" = None) -> None:
    p = argparse.ArgumentParser(description=__doc__)
    add_training_args(p)
    p.add_argument("--image", default="gcr.io/PROJECT/torchft-tpu:latest")
    p.add_argument("--tpu-type", default="tpu-v5p-slice")
    # defaults must agree: GKE TPU scheduling requires the google.com/tpu
    # request to match the selected topology's chip count (2x2x1 = 4 chips)
    p.add_argument("--tpu-topology", default="2x2x1",
                   help="per-replica-group slice topology; its chip count "
                        "must equal --chips-per-slice (v5p 2x2x1 = 4). "
                        "Single-host topologies only: the generated Job is "
                        "one pod per group (GROUP_WORLD_SIZE=1); multi-host "
                        "slices need an indexed Job with per-host pods")
    p.add_argument("--chips-per-slice", type=int, default=0,
                   help="TPU chips requested per pod (0 = derive from the "
                        "topology product; an override must agree with it)")
    p.add_argument("--fsdp", type=int, default=0,
                   help="in-group ZeRO shard degree (0 = fill the slice)")
    p.add_argument("--out", default="-", help="output file ('-' = stdout)")
    p.add_argument("--apply", action="store_true",
                   help="kubectl apply the generated manifests")
    args = p.parse_args(argv)

    yaml_text = build_manifests(args)
    if args.out == "-":
        sys.stdout.write(yaml_text)
    else:
        with open(args.out, "w") as f:
            f.write(yaml_text)
        print(f"wrote {args.out}")
    if args.apply:
        subprocess.run(["kubectl", "apply", "-f", "-"],
                       input=yaml_text.encode(), check=True)


if __name__ == "__main__":
    main()
