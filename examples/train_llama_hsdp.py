"""Fault-tolerant HSDP Llama training (reference: examples/slurm/runner.py's
torchtitan Llama-3-8B FT-HSDP job; FSDP2 set_all_reduce_hook integration,
fsdp_test.py:57-72).

Each replica group is one process owning an in-group XLA SPMD mesh
(fsdp × sp × tp over its chips — ZeRO sharding, ring attention, tensor
parallel, all in-graph over ICI). Fault tolerance runs *across* replica
groups on the replicated dim: per-step quorum, Manager.allreduce of the
grad pytree over DCN, two-phase commit, live HTTP recovery on rejoin —
the analog of hooking FSDP2's replicated-dim all-reduce into the manager.

Local smoke demo (2 groups × 4 virtual chips each on one host):

    python examples/train_llama_hsdp.py --demo --config tiny

Cluster use: start one lighthouse; launch one process per replica group with
REPLICA_GROUP_ID / TORCHFT_LIGHTHOUSE set (e.g. via torchft_tpu.launcher),
--config llama3_8b --fsdp 16 --sp 4 --tp 4. Chaos-test with
examples/punisher.py kill_loop.
"""

import argparse
import os
import sys
import time

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))


def train(args) -> None:
    if args.virtual_chips:
        from torchft_tpu.utils import force_virtual_cpu_devices

        force_virtual_cpu_devices(args.virtual_chips)

    import jax
    import jax.numpy as jnp
    import numpy as np
    import optax
    from jax.sharding import NamedSharding, PartitionSpec as P

    from torchft_tpu.manager import Manager
    from torchft_tpu.models.llama import CONFIGS, llama_init, llama_loss
    from torchft_tpu.parallel.mesh import (
        batch_sharding,
        llama_param_specs,
        make_hsdp_mesh,
        shard_params,
    )
    from torchft_tpu.parallel.ring_attention import make_ring_attention_fn
    from torchft_tpu.parallel.ulysses import make_ulysses_attention_fn
    from torchft_tpu.process_group import ProcessGroupHost

    replica_id = int(os.environ.get("REPLICA_GROUP_ID", args.replica_id))
    lighthouse = os.environ.get("TORCHFT_LIGHTHOUSE", args.lighthouse)
    cfg = CONFIGS[args.config]

    # In-group mesh: dp=1 (the replicated dim lives across groups, via the
    # manager), everything else in-graph over ICI.
    mesh = make_hsdp_mesh(dp=1, fsdp=args.fsdp, sp=args.sp, tp=args.tp)
    specs = llama_param_specs(cfg)
    param_shardings = jax.tree_util.tree_map(lambda s: NamedSharding(mesh, s), specs)
    tok_sharding = batch_sharding(mesh)
    attention_fn = (
        make_ulysses_attention_fn(mesh) if args.attention == "ulysses"
        else make_ring_attention_fn(mesh)
    )

    params = shard_params(
        llama_init(jax.random.PRNGKey(replica_id), cfg), mesh, specs
    )
    tx = optax.adamw(args.lr, weight_decay=0.1)
    opt_state = tx.init(params)

    # FT split of the train step: grads in-graph (reduced over fsdp/sp by
    # XLA), FT allreduce across groups on the host plane, then update.
    @jax.jit
    def grad_step(params, tokens, targets):
        # remat="full": the 8B seq-8192 target sits at the HBM edge; the
        # "dots" default is tuned for configs with headroom (see models/remat).
        return jax.value_and_grad(llama_loss)(
            params, tokens, targets, cfg, attention_fn=attention_fn, remat="full"
        )

    @jax.jit
    def update_step(params, opt_state, grads):
        updates, opt_state = tx.update(grads, opt_state, params)
        return optax.apply_updates(params, updates), opt_state

    state = {"params": params, "opt_state": opt_state}

    def load_state(sd):
        def place(t, x):
            # mesh-sharded leaves are committed back onto their sharding;
            # everything else (e.g. optimizer step counters, which tx.init
            # left on the default device) stays uncommitted so jit remains
            # free to place it — committing a scalar to one device while
            # params commit to the mesh makes the jitted step reject the mix
            if isinstance(t, jax.Array):
                if isinstance(t.sharding, NamedSharding):
                    return jax.device_put(jnp.asarray(x, dtype=t.dtype),
                                          t.sharding)
                # via host: restored leaves may arrive as arrays already
                # committed to one device, and committedness survives
                # jnp.asarray
                return jnp.asarray(np.asarray(x), dtype=t.dtype)
            return x

        state["params"] = jax.tree_util.tree_map(
            place, state["params"], sd["params"]
        )
        state["opt_state"] = jax.tree_util.tree_map(
            place, state["opt_state"], sd["opt_state"]
        )

    # tier-2 durable checkpoints (tier 1 = live healing between replicas)
    ckpt = None
    if args.ckpt_dir:
        from torchft_tpu.checkpointing import DurableCheckpointer

        ckpt = DurableCheckpointer(
            os.path.join(args.ckpt_dir, f"replica_{replica_id}"),
            save_interval_steps=args.ckpt_every,
        )

    # Both transports heal with an IN-PLACE template: received leaves land
    # directly on this replica's NamedShardings (HBM-to-HBM on real chips;
    # load_state's device_put fallback then has nothing to repair — safe
    # under async quorum because device-leaf templates never mutate live
    # buffers at receive time). The template is the Manager's own live
    # composite (late-bound: `manager` is assigned below), so leaf
    # alignment with the sender's tree holds by construction — under
    # --diloco the fragment state fns register on BOTH sides and the
    # composite trees still match.
    recovery_pg = None
    if args.transport == "pg":
        from torchft_tpu.checkpointing import PGTransport

        recovery_pg = ProcessGroupHost(timeout=args.timeout)  # caller-owned
        transport = PGTransport(
            recovery_pg,
            timeout=args.timeout,
            state_dict_template=lambda: manager.state_dict_template(),
        )
    else:
        from torchft_tpu.checkpointing import HTTPTransport

        transport = HTTPTransport(
            timeout=args.timeout,
            state_dict_template=lambda: manager.state_dict_template(),
        )

    manager = Manager(
        pg=ProcessGroupHost(timeout=args.timeout),
        load_state_dict=load_state,
        state_dict=lambda: {"params": state["params"], "opt_state": state["opt_state"]},
        min_replica_size=args.min_replica_size,
        use_async_quorum=not args.diloco,  # DiLoCo requires sync quorum
        replica_id=f"llama_hsdp_{replica_id}",
        lighthouse_addr=lighthouse,
        timeout=args.timeout,
        checkpoint_transport=transport,
    )

    diloco = None
    if args.diloco:
        # Semi-sync: inner adamw steps run purely in-group; every
        # sync_every steps one fragment's pseudogradient is averaged across
        # replica groups and applied by the outer optimizer (reference
        # semi-sync config, examples/slurm/runner.py: sync_steps 20,
        # 2 fragments, 1-step delay).
        from torchft_tpu.local_sgd import DiLoCo

        diloco = DiLoCo(
            manager, state["params"],
            outer_tx=optax.sgd(args.outer_lr, momentum=0.9, nesterov=True),
            sync_every=args.sync_every,
            num_fragments=args.num_fragments,
            fragment_sync_delay=args.fragment_sync_delay,
            should_quantize=args.quantize,
            # after a live heal the quorum rebinds state["params"]; this
            # lets DiLoCo re-read them instead of using stale leaves
            get_params=lambda: state["params"],
        )

    # restore AFTER every state-dict fn is registered (trainer state above,
    # DiLoCo fragments in the constructor) so a cold restart recovers the
    # full composite — including fragment globals and outer-optimizer
    # momentum — not just params/opt_state; then resume the quorum clock.
    if ckpt is not None:
        restored = ckpt.restore(state_template=manager.user_state_dict())
        if restored is not None:
            user_sd, manager_sd, _ = restored
            manager.load_user_state_dict(user_sd)
            if manager_sd is not None:
                manager.load_state_dict(manager_sd)
            print(f"[replica {replica_id}] restored durable checkpoint "
                  f"step={manager.current_step()}", flush=True)

    rng = np.random.RandomState(replica_id)
    B, S = args.batch_size, args.seq_len
    print(f"[replica {replica_id}] mesh fsdp={args.fsdp} sp={args.sp} tp={args.tp} "
          f"diloco={bool(diloco)} starting at step {manager.current_step()}",
          flush=True)
    t0, tokens_done = time.monotonic(), 0
    # --steps counts inner optimizer steps in both modes. manager.current_step
    # only advances on committed quorums — in DiLoCo mode that is one per
    # sync_every/num_fragments inner steps, so gating the loop on it would
    # run sync_every/num_fragments times more compute than asked for. A
    # restarted replica learns the global step only at its first quorum
    # (inside diloco.step), so the inner count is re-clamped to the global
    # progress after every boundary rather than once up front.
    inner_step = 0
    if diloco is not None:
        # the authoritative per-fragment cycle length: DiLoCo recomputes the
        # fragment count from the actual partition, so re-deriving it from
        # the CLI args could disagree with the real quorum cadence
        per_cycle = diloco._sync_every
        done = lambda: inner_step >= args.steps  # noqa: E731
    else:
        per_cycle = 0  # unused
        done = lambda: manager.current_step() >= args.steps  # noqa: E731
    # try/finally: the abandoned-commit-round protection (flush) and the
    # checkpoint/manager teardown must run on SIGINT/preemption/exception
    # exits too, not just the clean path
    try:
        while not done():
            batch = jax.device_put(
                jnp.asarray(rng.randint(0, cfg.vocab_size, size=(B, S))), tok_sharding
            )
            if diloco is not None:
                # inner step: local grads + local adamw, no cross-group traffic
                loss, grads = grad_step(state["params"], batch, batch)
                state["params"], state["opt_state"] = update_step(
                    state["params"], state["opt_state"], grads
                )
                # on a heal, diloco.step re-reads state["params"] via get_params
                # and returns the healed pytree
                state["params"] = diloco.step(state["params"])
                # resume/catch-up: committed quorums are the global clock
                inner_step = max(inner_step + 1,
                                 manager.current_step() * per_cycle)
                tokens_done += B * S
            else:
                manager.start_quorum()
                loss, grads = grad_step(state["params"], batch, batch)
                reduced = manager.allreduce(grads).get_future().wait(
                    timeout=args.timeout
                )
                if not manager.should_commit():
                    continue
                state["params"], state["opt_state"] = update_step(
                    state["params"], state["opt_state"], reduced
                )
                tokens_done += B * S * manager.num_participants()
                inner_step += 1
            # gate on the count that actually advances every loop iteration:
            # in DiLoCo mode manager.current_step is constant across a whole
            # inner window (bursty/silent logs); inner_step is not
            if ckpt is not None:
                # lazy: the full registered composite (trainer + algorithm
                # state) is only materialized on the save interval
                ckpt.maybe_save(manager.current_step(), manager.user_state_dict,
                                manager=manager)
            if inner_step % args.log_every == 0:
                dt = time.monotonic() - t0
                print(
                    f"[replica {replica_id}] step={manager.current_step()} "
                    f"inner={inner_step} loss={float(loss):.4f} "
                    f"participants={manager.num_participants()} "
                    f"tok/s={tokens_done / max(dt, 1e-6):.0f}",
                    flush=True,
                )
    finally:
        try:
            if diloco is not None:
                # the loop may stop between a fragment's prepare and perform
                # boundaries (or be interrupted there); finish the in-flight
                # sync so peers aren't left waiting on an abandoned commit
                # round. Best-effort: a flush failing on a dead wire must
                # not mask the original exception or skip the teardown.
                state["params"] = diloco.flush(state["params"])
        except Exception as e:  # noqa: BLE001
            print(f"[replica {replica_id}] flush failed during teardown: {e}",
                  flush=True)
        finally:
            if ckpt is not None:
                ckpt.close()
            manager.shutdown(wait=False)
            if recovery_pg is not None:
                recovery_pg.shutdown()  # caller-owned (PGTransport never touches it)
    print(f"[replica {replica_id}] done", flush=True)


def demo(args) -> None:
    import subprocess

    from torchft_tpu.coordination import LighthouseServer

    lh = LighthouseServer(
        bind="127.0.0.1:0", min_replicas=1, join_timeout_ms=500,
        quorum_tick_ms=50, heartbeat_timeout_ms=2000,
    )
    addr = f"127.0.0.1:{lh.port}"
    print(f"lighthouse at http://{addr}/", flush=True)

    def spawn(rid):
        env = dict(os.environ, TORCHFT_LIGHTHOUSE=addr, REPLICA_GROUP_ID=str(rid))
        return subprocess.Popen(
            # ulysses needs sp>1 and sp | per-device head counts: drop tp
            # and give sp the pair so the all_to_all path actually runs
            [sys.executable, __file__, "--config", args.config,
             "--steps", str(args.steps), "--virtual-chips", "4",
             "--fsdp", "2",
             *(["--sp", "2", "--tp", "1"] if args.attention == "ulysses"
               else ["--sp", "1", "--tp", "2"]),
             "--attention", args.attention,
             "--transport", args.transport,
             "--batch-size", str(args.batch_size), "--seq-len", str(args.seq_len)],
            env=env,
        )

    procs = {rid: spawn(rid) for rid in range(args.replicas)}
    time.sleep(args.kill_after)
    victim = args.replicas - 1
    print(f"--- killing replica {victim} ---", flush=True)
    procs[victim].kill()
    procs[victim].wait()
    time.sleep(2)
    print(f"--- restarting replica {victim} ---", flush=True)
    procs[victim] = spawn(victim)

    rc = 0
    try:
        for rid, p in procs.items():
            try:
                rc |= p.wait(timeout=600)
            except subprocess.TimeoutExpired:
                # a wedged replica must not orphan its siblings or skip
                # lighthouse shutdown
                print(f"--- replica {rid} wedged; killing ---", flush=True)
                p.kill()
                p.wait()
                rc |= 1
    finally:
        for p in procs.values():
            if p.poll() is None:
                p.kill()
        lh.shutdown()
    print("demo finished rc=", rc, flush=True)
    sys.exit(rc)


if __name__ == "__main__":
    parser = argparse.ArgumentParser()
    # choices from CONFIGS itself: the list can't drift when configs are
    # added, and a typo dies at argparse instead of as a KeyError in every
    # spawned replica (importing CONFIGS imports jax but no backend init)
    from torchft_tpu.models.llama import CONFIGS

    parser.add_argument("--config", default="tiny", choices=sorted(CONFIGS),
                        help="model config (CONFIGS key)")
    parser.add_argument("--steps", type=int, default=10)
    parser.add_argument("--batch-size", type=int, default=4)
    parser.add_argument("--seq-len", type=int, default=128)
    parser.add_argument("--lr", type=float, default=3e-4)
    parser.add_argument("--fsdp", type=int, default=1)
    parser.add_argument("--sp", type=int, default=1)
    parser.add_argument("--tp", type=int, default=1)
    parser.add_argument("--attention", choices=["ring", "ulysses"],
                        default="ring",
                        help="sequence-parallel strategy over sp: ring "
                             "(default; no head-count constraint) or "
                             "ulysses (all-to-all; sp must divide the "
                             "per-device head counts)")
    parser.add_argument("--min-replica-size", type=int, default=1)
    parser.add_argument("--transport", choices=["http", "pg"], default="http",
                        help="live-healing transport: http (default) or pg "
                             "(dedicated recovery PG, in-place receive onto "
                             "this replica's shardings)")
    parser.add_argument("--timeout", type=float, default=60.0)
    parser.add_argument("--diloco", action="store_true",
                        help="semi-sync across groups (DiLoCo) instead of "
                             "per-step gradient allreduce")
    parser.add_argument("--sync-every", type=int, default=20)
    parser.add_argument("--num-fragments", type=int, default=2)
    parser.add_argument("--fragment-sync-delay", type=int, default=1)
    parser.add_argument("--outer-lr", type=float, default=0.7)
    parser.add_argument("--quantize", action="store_true",
                        help="fp8-compress the pseudogradient allreduce")
    parser.add_argument("--log-every", type=int, default=1)
    parser.add_argument("--ckpt-dir", default="",
                        help="directory for tier-2 durable checkpoints "
                             "(empty = live healing only)")
    parser.add_argument("--ckpt-every", type=int, default=100,
                        help="durable-checkpoint interval in committed steps")
    parser.add_argument("--replica-id", type=int, default=0)
    parser.add_argument("--lighthouse", type=str, default="127.0.0.1:29510")
    parser.add_argument("--virtual-chips", type=int, default=0,
                        help="force N virtual CPU devices (testing)")
    parser.add_argument("--demo", action="store_true")
    parser.add_argument("--replicas", type=int, default=2)
    parser.add_argument("--kill-after", type=float, default=20.0)
    args = parser.parse_args()
    if args.demo:
        demo(args)
    else:
        train(args)
