"""Chaos tool: kill replicas through the lighthouse to exercise fault
tolerance (reference: examples/slurm/punisher.py).

Modes:
  kill_one   — kill one (random or named) replica and exit
  kill_all   — kill every replica in the current quorum
  kill_loop  — Poisson process of kills with the given MTBF until stopped

The lighthouse serves ``/status`` (JSON: participants + heartbeat ages) and
``POST /replica/{id}/kill`` which forwards a Kill RPC to the replica's
manager (native/lighthouse.cc handle_http); managers exit(1) on kill, and
the launcher/torchelastic equivalent restarts them — the quorum shrinks and
re-grows while training keeps going.

    python examples/punisher.py --lighthouse 127.0.0.1:29510 kill_one
    python examples/punisher.py --lighthouse 127.0.0.1:29510 kill_loop --mtbf 60
"""

import argparse
import json
import random
import sys
import time
import urllib.request


def _base(addr: str) -> str:
    return addr if addr.startswith("http") else f"http://{addr}"


def list_replicas(lighthouse: str) -> list:
    with urllib.request.urlopen(f"{_base(lighthouse)}/status", timeout=10) as r:
        status = json.loads(r.read().decode())
    # top-level participants are BARE replica-id strings (replicas blocked
    # in a quorum call right now); prev_quorum participants are member
    # objects. Handle both shapes.
    ids = {
        p if isinstance(p, str) else p["replica_id"]
        for p in status.get("participants", [])
    }
    if status.get("prev_quorum"):
        ids |= {p["replica_id"] for p in status["prev_quorum"].get("participants", [])}
    return sorted(ids)


def kill(lighthouse: str, replica_id: str) -> bool:
    req = urllib.request.Request(
        f"{_base(lighthouse)}/replica/{replica_id}/kill", method="POST"
    )
    try:
        with urllib.request.urlopen(req, timeout=10) as r:
            print(f"killed {replica_id}: {r.read().decode().strip()}", flush=True)
        return True
    except urllib.error.HTTPError as e:
        print(f"kill {replica_id} failed: {e}", file=sys.stderr, flush=True)
        return False


def kill_one(lighthouse: str, replica_id: "str | None" = None) -> int:
    replicas = list_replicas(lighthouse)
    if not replicas:
        print("no replicas known to the lighthouse", file=sys.stderr)
        return 1
    victim = replica_id if replica_id is not None else random.choice(replicas)
    return 0 if kill(lighthouse, victim) else 1


def kill_all(lighthouse: str) -> int:
    replicas = list_replicas(lighthouse)
    rc = 0
    for r in replicas:
        rc |= 0 if kill(lighthouse, r) else 1
    return rc


def kill_loop(lighthouse: str, mtbf: float, max_kills: int = 0) -> int:
    """Exponentially distributed inter-kill times with mean ``mtbf`` seconds
    (reference punisher's MTBF loop)."""
    kills = 0
    while max_kills <= 0 or kills < max_kills:
        delay = random.expovariate(1.0 / mtbf)
        print(f"next kill in {delay:.1f}s", flush=True)
        time.sleep(delay)
        try:
            if kill_one(lighthouse) == 0:
                kills += 1
        except Exception as e:  # noqa: BLE001 — lighthouse may be mid-restart
            print(f"kill attempt failed: {e}", file=sys.stderr, flush=True)
    return 0


def main() -> None:
    parser = argparse.ArgumentParser(description=__doc__)
    parser.add_argument("--lighthouse", required=True, help="host:port")
    sub = parser.add_subparsers(dest="cmd", required=True)
    one = sub.add_parser("kill_one")
    one.add_argument("--replica-id", default=None)
    sub.add_parser("kill_all")
    loop = sub.add_parser("kill_loop")
    loop.add_argument("--mtbf", type=float, default=60.0,
                      help="mean seconds between kills")
    loop.add_argument("--max-kills", type=int, default=0, help="0 = forever")
    args = parser.parse_args()

    if args.cmd == "kill_one":
        sys.exit(kill_one(args.lighthouse, args.replica_id))
    elif args.cmd == "kill_all":
        sys.exit(kill_all(args.lighthouse))
    else:
        sys.exit(kill_loop(args.lighthouse, args.mtbf, args.max_kills))


if __name__ == "__main__":
    main()
