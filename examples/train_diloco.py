"""Streaming DiLoCo training example (reference: train_diloco.py).

Each replica group trains a multi-layer MLP locally with AdamW and
synchronizes pseudo-gradients every ``--sync-every`` steps through the
fault-tolerant manager, with the model split into fragments that sync
staggered (streaming DiLoCo). Run the demo:

    python examples/train_diloco.py --demo
"""

import argparse
import os
import sys
import time

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))


def train(args) -> None:
    if args.virtual_chips:
        # local multi-process runs share no TPU; use a virtual CPU platform
        from torchft_tpu.utils import force_virtual_cpu_devices

        force_virtual_cpu_devices(args.virtual_chips)

    import jax
    import jax.numpy as jnp
    import numpy as np
    import optax

    from torchft_tpu.local_sgd import DiLoCo
    from torchft_tpu.manager import Manager
    from torchft_tpu.process_group import ProcessGroupHost

    replica_id = int(os.environ.get("REPLICA_GROUP_ID", args.replica_id))
    lighthouse = os.environ.get("TORCHFT_LIGHTHOUSE", args.lighthouse)

    # multi-layer MLP (the reference uses MultiMLP split via pipelining into
    # fragments; fragments here are pytree partitions)
    def init_params(key):
        dims = [32, 64, 64, 64, 10]
        keys = jax.random.split(key, len(dims) - 1)
        return {
            f"layer{i}": {
                "w": jax.random.normal(keys[i], (dims[i], dims[i + 1]), jnp.float32)
                * (1.0 / np.sqrt(dims[i])),
                "b": jnp.zeros((dims[i + 1],), jnp.float32),
            }
            for i in range(len(dims) - 1)
        }

    def forward(params, x):
        h = x
        n = len(params)
        for i in range(n):
            layer = params[f"layer{i}"]
            h = h @ layer["w"] + layer["b"]
            if i < n - 1:
                h = jax.nn.relu(h)
        return h

    def loss_fn(params, x, y):
        logits = forward(params, x)
        return optax.softmax_cross_entropy_with_integer_labels(logits, y).mean()

    params = init_params(jax.random.PRNGKey(replica_id))
    inner_tx = optax.adamw(1e-3)
    inner_state = inner_tx.init(params)

    state = {"params": params, "inner": inner_state}

    def load_state(sd):
        state["params"] = jax.tree_util.tree_map(jnp.asarray, sd["params"])

    def save_state():
        return {"params": state["params"]}

    manager = Manager(
        pg=ProcessGroupHost(timeout=30.0),
        load_state_dict=load_state,
        state_dict=save_state,
        min_replica_size=args.min_replica_size,
        use_async_quorum=False,  # DiLoCo requirement
        replica_id=f"train_diloco_{replica_id}",
        lighthouse_addr=lighthouse,
        timeout=30.0,
    )

    diloco = DiLoCo(
        manager,
        state["params"],
        outer_tx=optax.sgd(args.outer_lr, momentum=0.9, nesterov=True),
        sync_every=args.sync_every,
        num_fragments=args.num_fragments,
        fragment_sync_delay=args.fragment_sync_delay,
        fragment_update_alpha=args.fragment_update_alpha,
        # a live heal rebinds state["params"]; DiLoCo must re-read them
        # instead of computing pseudogradients from stale pre-heal leaves
        get_params=lambda: state["params"],
    )

    rng = np.random.RandomState(replica_id)

    def _inner(params, opt_state, x, y):
        loss, grads = jax.value_and_grad(loss_fn)(params, x, y)
        updates, opt_state = inner_tx.update(grads, opt_state, params)
        return optax.apply_updates(params, updates), opt_state, loss

    inner_step = jax.jit(_inner)

    target_outer_steps = args.steps // args.sync_every * args.num_fragments
    local = 0
    try:
        while manager.current_step() < target_outer_steps:
            x = jnp.asarray(rng.randn(args.batch_size, 32), jnp.float32)
            y = jnp.asarray(rng.randint(0, 10, size=(args.batch_size,)))
            state["params"], state["inner"], loss = inner_step(
                state["params"], state["inner"], x, y
            )
            state["params"] = diloco.step(state["params"])
            local += 1
            if local % args.sync_every == 0:
                print(
                    f"[replica {replica_id}] outer_step={manager.current_step()} "
                    f"local={local} loss={float(loss):.4f}",
                    flush=True,
                )
    finally:
        try:
            # never strand peers on an in-flight commit round, even on
            # interrupted exits; best-effort — a flush failing on a dead
            # wire must not mask the original exception or skip shutdown
            state["params"] = diloco.flush(state["params"])
        except Exception as e:  # noqa: BLE001
            print(f"[replica {replica_id}] flush failed during teardown: {e}",
                  flush=True)
        finally:
            manager.shutdown(wait=False)
    w_sum = sum(
        float(jnp.sum(jnp.abs(diloco.fragments[i].original[0])))
        for i in range(len(diloco.fragments))
    )
    print(f"[replica {replica_id}] done: global_l1[frag0]={w_sum:.6f}", flush=True)


def demo(args) -> None:
    import subprocess

    from torchft_tpu.coordination import LighthouseServer

    lh = LighthouseServer(
        bind="127.0.0.1:0", min_replicas=1, join_timeout_ms=500,
        quorum_tick_ms=50, heartbeat_timeout_ms=2000,
    )
    addr = f"127.0.0.1:{lh.port}"
    print(f"lighthouse at http://{addr}/", flush=True)

    def spawn(rid):
        env = dict(os.environ, TORCHFT_LIGHTHOUSE=addr, REPLICA_GROUP_ID=str(rid))
        return subprocess.Popen(
            [sys.executable, __file__, "--steps", str(args.steps),
             "--batch-size", str(args.batch_size),
             "--sync-every", str(args.sync_every),
             "--num-fragments", str(args.num_fragments),
             "--virtual-chips", "1"],
            env=env,
        )

    procs = {rid: spawn(rid) for rid in range(args.replicas)}
    time.sleep(args.kill_after)
    victim = args.replicas - 1
    print(f"--- killing replica {victim} ---", flush=True)
    procs[victim].kill()
    procs[victim].wait()
    time.sleep(1)
    print(f"--- restarting replica {victim} ---", flush=True)
    procs[victim] = spawn(victim)

    rc = 0
    try:
        for rid, p in procs.items():
            try:
                rc |= p.wait(timeout=300)
            except subprocess.TimeoutExpired:
                # a wedged replica must not orphan its siblings or skip
                # lighthouse shutdown
                print(f"--- replica {rid} wedged; killing ---", flush=True)
                p.kill()
                p.wait()
                rc |= 1
    finally:
        for p in procs.values():
            if p.poll() is None:
                p.kill()
        lh.shutdown()
    print("demo finished rc=", rc, flush=True)
    sys.exit(rc)


if __name__ == "__main__":
    parser = argparse.ArgumentParser()
    parser.add_argument("--steps", type=int, default=40)
    parser.add_argument("--batch-size", type=int, default=16)
    parser.add_argument("--outer-lr", type=float, default=0.7)
    parser.add_argument("--sync-every", type=int, default=4)
    parser.add_argument("--num-fragments", type=int, default=2)
    parser.add_argument("--fragment-sync-delay", type=int, default=0)
    parser.add_argument("--fragment-update-alpha", type=float, default=0.0)
    parser.add_argument("--virtual-chips", type=int, default=0,
                        help="force N virtual CPU devices (local multi-process runs)")
    parser.add_argument("--min-replica-size", type=int, default=1)
    parser.add_argument("--replica-id", type=int, default=0)
    parser.add_argument("--lighthouse", type=str, default="127.0.0.1:29510")
    parser.add_argument("--demo", action="store_true")
    parser.add_argument("--replicas", type=int, default=2)
    parser.add_argument("--kill-after", type=float, default=8.0)
    args = parser.parse_args()
    if args.demo:
        demo(args)
    else:
        train(args)
