"""Fault-tolerant data-parallel training example (reference: train_ddp.py).

Each replica group is one process training a small CNN on synthetic
CIFAR-10-shaped data with optax, fault-tolerant across replica groups via
torchft_tpu: per-step quorum, managed allreduce of the grad pytree, two-phase
commit, live recovery over HTTP on rejoin.

Run a 2-replica demo (spawns lighthouse + replicas, kills one mid-run):

    python examples/train_ddp.py --demo

Or run components manually:

    python -m torchft_tpu.lighthouse --bind 0.0.0.0:29510 &
    TORCHFT_LIGHTHOUSE=127.0.0.1:29510 REPLICA_GROUP_ID=0 python examples/train_ddp.py
    TORCHFT_LIGHTHOUSE=127.0.0.1:29510 REPLICA_GROUP_ID=1 python examples/train_ddp.py
"""

import argparse
import os
import sys
import time

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))


def build_trainer(replica_id: int = 0, batch_size: int = 8, lr: float = 0.01):
    """The example's model/optimizer/step, importable as a unit.

    Returns ``(state, grad_fn, optimizer, make_batch)`` so harnesses can run
    the REAL trainer loop this example trains (benchmarks/ft_overhead_bench.py
    measures its per-step cost bare vs. under a live Manager).
    """
    import jax
    import jax.numpy as jnp
    import numpy as np
    import optax

    # -- model: tiny CNN on 32x32x3 inputs --------------------------------
    def init_params(key):
        k1, k2, k3 = jax.random.split(key, 3)
        return {
            "conv": jax.random.normal(k1, (3, 3, 3, 16), jnp.float32) * 0.1,
            "w1": jax.random.normal(k2, (16 * 16 * 16, 64), jnp.float32) * 0.05,
            "w2": jax.random.normal(k3, (64, 10), jnp.float32) * 0.05,
        }

    def forward(params, x):
        h = jax.lax.conv_general_dilated(
            x, params["conv"], window_strides=(2, 2), padding="SAME",
            dimension_numbers=("NHWC", "HWIO", "NHWC"),
        )
        h = jax.nn.relu(h)
        h = h.reshape(h.shape[0], -1)
        h = jax.nn.relu(h @ params["w1"])
        return h @ params["w2"]

    def loss_fn(params, x, y):
        logits = forward(params, x)
        return optax.softmax_cross_entropy_with_integer_labels(logits, y).mean()

    grad_fn = jax.jit(jax.value_and_grad(loss_fn))

    # Different init per replica: init_sync recovers everyone from the primary.
    params = init_params(jax.random.PRNGKey(replica_id))
    optimizer = optax.sgd(lr, momentum=0.9)
    opt_state = optimizer.init(params)
    state = {"params": params, "opt_state": opt_state}

    rng = np.random.RandomState(replica_id)

    def make_batch():
        # synthetic batch, sharded per replica (DistributedSampler equivalent)
        x = jnp.asarray(rng.randn(batch_size, 32, 32, 3), jnp.float32)
        y = jnp.asarray(rng.randint(0, 10, size=(batch_size,)))
        return x, y

    return state, grad_fn, optimizer, make_batch


def train(args) -> None:
    if args.virtual_chips:
        # local multi-process runs share no TPU; use a virtual CPU platform
        from torchft_tpu.utils import force_virtual_cpu_devices

        force_virtual_cpu_devices(args.virtual_chips)
    import jax
    import jax.numpy as jnp
    import numpy as np

    from torchft_tpu.manager import Manager
    from torchft_tpu.process_group import ProcessGroupHost

    replica_id = int(os.environ.get("REPLICA_GROUP_ID", args.replica_id))
    lighthouse = os.environ.get("TORCHFT_LIGHTHOUSE", args.lighthouse)

    state, grad_fn, optimizer, _make_batch = build_trainer(
        replica_id, args.batch_size, args.lr
    )
    opt_state = state["opt_state"]

    def load_state(sd):
        state["params"] = jax.tree_util.tree_map(jnp.asarray, sd["params"])
        state["opt_state"] = jax.tree_util.tree_map(
            lambda t, x: jnp.asarray(x) if hasattr(t, "dtype") else x,
            opt_state, sd["opt_state"],
        )

    def save_state():
        return {"params": state["params"], "opt_state": state["opt_state"]}

    # --transport pg mirrors the reference train_ddp default (PGTransport,
    # train_ddp.py:91-110): healing rides a DEDICATED recovery PG that the
    # Manager re-rendezvouses with every quorum (the host plane forbids
    # mixing p2p and collective traffic on one PG generation, so unlike
    # the reference the recovery PG is a separate instance).
    transport = recovery_pg = None
    if args.transport == "pg":
        from torchft_tpu.checkpointing import PGTransport

        recovery_pg = ProcessGroupHost(timeout=30.0)  # caller-owned
        transport = PGTransport(recovery_pg, timeout=30.0)

    manager = Manager(
        pg=ProcessGroupHost(timeout=30.0),
        load_state_dict=load_state,
        state_dict=save_state,
        min_replica_size=args.min_replica_size,
        replica_id=f"train_ddp_{replica_id}",
        lighthouse_addr=lighthouse,
        timeout=30.0,
        checkpoint_transport=transport,
    )

    rng = np.random.RandomState(replica_id)
    print(f"[replica {replica_id}] starting at step {manager.current_step()}", flush=True)
    try:
        _train_loop(args, manager, state, grad_fn, optimizer, rng, replica_id)
    finally:
        manager.shutdown(wait=False)
        if recovery_pg is not None:
            recovery_pg.shutdown()  # PGTransport.shutdown never touches it


def _train_loop(args, manager, state, grad_fn, optimizer, rng, replica_id) -> None:
    import jax
    import jax.numpy as jnp
    import optax

    accum = max(1, getattr(args, "grad_accum", 1))
    quantize = bool(getattr(args, "quantize", False))
    while manager.current_step() < args.steps:
        # synthetic batch, sharded per replica (DistributedSampler equivalent)
        x = jnp.asarray(rng.randn(args.batch_size, 32, 32, 3), jnp.float32)
        y = jnp.asarray(rng.randint(0, 10, size=(args.batch_size,)))

        manager.start_quorum()
        if accum > 1:
            # Gradient accumulation over the streaming pipeline: each
            # microbatch's streamed allreduce starts reducing its buckets
            # while the NEXT microbatch's grad_fn runs, so the wire rides
            # under compute. Allreduce is linear, so averaging the reduced
            # microbatch means equals reducing the accumulated mean.
            # --quantize streams the same buckets fp8-compressed with
            # error feedback — it no longer drops to the serial
            # unbucketed path (tests/test_examples_smoke.py pins this).
            streams = []
            for k in range(accum):
                if k > 0:
                    x = jnp.asarray(
                        rng.randn(args.batch_size, 32, 32, 3), jnp.float32
                    )
                    y = jnp.asarray(rng.randint(0, 10, size=(args.batch_size,)))
                loss, grads = grad_fn(state["params"], x, y)
                streams.append(
                    manager.allreduce_streamed(
                        grads, should_quantize=quantize
                    )
                )
            reduced_trees = [s.wait(timeout=60) for s in streams]
            reduced = jax.tree_util.tree_map(
                lambda *vs: sum(jnp.asarray(v) for v in vs) / len(vs),
                *reduced_trees,
            )
        else:
            loss, grads = grad_fn(state["params"], x, y)
            reduced = manager.allreduce(
                grads, should_quantize=quantize
            ).get_future().wait(timeout=60)
        if manager.should_commit():
            updates, new_opt_state = optimizer.update(
                jax.tree_util.tree_map(jnp.asarray, reduced),
                state["opt_state"], state["params"],
            )
            state["params"] = optax.apply_updates(state["params"], updates)
            state["opt_state"] = new_opt_state
            print(
                f"[replica {replica_id}] step={manager.current_step()} "
                f"loss={float(loss):.4f} participants={manager.num_participants()}",
                flush=True,
            )
    w_sum = float(jnp.sum(jnp.abs(state["params"]["w2"])))
    print(f"[replica {replica_id}] done: w2_l1={w_sum:.6f}", flush=True)


def demo(args) -> None:
    """Spawn lighthouse + N replicas, kill one mid-run, watch it recover."""
    import subprocess

    from torchft_tpu.coordination import LighthouseServer

    lh = LighthouseServer(
        bind="127.0.0.1:0", min_replicas=1, join_timeout_ms=500,
        quorum_tick_ms=50, heartbeat_timeout_ms=2000,
    )
    addr = f"127.0.0.1:{lh.port}"
    print(f"lighthouse at http://{addr}/ (dashboard)", flush=True)

    def spawn(rid):
        env = dict(os.environ, TORCHFT_LIGHTHOUSE=addr, REPLICA_GROUP_ID=str(rid))
        return subprocess.Popen(
            [sys.executable, __file__, "--steps", str(args.steps),
             "--batch-size", str(args.batch_size),
             "--transport", args.transport,
             "--virtual-chips", "1"],
            env=env,
        )

    procs = {rid: spawn(rid) for rid in range(args.replicas)}
    time.sleep(args.kill_after)
    victim = args.replicas - 1
    print(f"--- killing replica {victim} ---", flush=True)
    procs[victim].kill()
    procs[victim].wait()
    time.sleep(2)
    print(f"--- restarting replica {victim} ---", flush=True)
    procs[victim] = spawn(victim)

    rc = 0
    try:
        for rid, p in procs.items():
            try:
                rc |= p.wait(timeout=300)
            except subprocess.TimeoutExpired:
                # a wedged replica must not orphan its siblings or skip
                # lighthouse shutdown
                print(f"--- replica {rid} wedged; killing ---", flush=True)
                p.kill()
                p.wait()
                rc |= 1
    finally:
        for p in procs.values():
            if p.poll() is None:
                p.kill()
        lh.shutdown()
    print("demo finished rc=", rc, flush=True)
    sys.exit(rc)


if __name__ == "__main__":
    parser = argparse.ArgumentParser()
    parser.add_argument("--steps", type=int, default=20)
    parser.add_argument("--batch-size", type=int, default=8)
    parser.add_argument("--grad-accum", type=int, default=1,
                        help="microbatches per step; >1 issues one STREAMED "
                             "allreduce per microbatch so bucket reduction "
                             "overlaps the next microbatch's grad_fn")
    parser.add_argument("--quantize", action="store_true",
                        help="stream gradient buckets fp8-compressed with "
                             "error feedback (TORCHFT_COMPRESS picks the "
                             "codec); composes with --grad-accum")
    parser.add_argument("--lr", type=float, default=0.01)
    parser.add_argument("--min-replica-size", type=int, default=1)
    parser.add_argument("--transport", choices=["http", "pg"], default="http",
                        help="live-healing transport: http (default) or pg "
                             "(dedicated recovery process group, the "
                             "reference train_ddp default)")
    parser.add_argument("--replica-id", type=int, default=0)
    parser.add_argument("--lighthouse", type=str, default="127.0.0.1:29510")
    parser.add_argument("--virtual-chips", type=int, default=0,
                        help="force N virtual CPU devices (local multi-process runs)")
    parser.add_argument("--demo", action="store_true")
    parser.add_argument("--replicas", type=int, default=2)
    parser.add_argument("--kill-after", type=float, default=6.0)
    args = parser.parse_args()
    if args.demo:
        demo(args)
    else:
        train(args)
