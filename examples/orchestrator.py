"""Actor-style orchestration of a fault-tolerant job
(reference: examples/monarch/train_distributed.py:27-442 — LighthouseActor,
TrainingActor/ReplicaActor, OrchestrationManager, FailureController).

Instead of Monarch's actor runtime, plain threads play the actor roles:

- ``LighthouseActor``  — owns the in-process lighthouse server
- ``ReplicaActor``     — supervises one replica group's worker subprocess;
  restarts it per the retry policy and reports state transitions
- ``FailureController``— injects failures (kill via the lighthouse HTTP
  endpoint) on a schedule to prove recovery
- ``OrchestrationManager`` — wires the actors, waits for completion, and
  reports a summary (restarts per replica, final status)

Demo (2 replica groups training the DDP example on virtual CPU chips, one
injected kill):

    python examples/orchestrator.py --replicas 2 --steps 40 --inject-kill-after 12
"""

import argparse
import os
import subprocess
import sys
import threading
import time
import urllib.request
from dataclasses import dataclass, field

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))

from torchft_tpu.coordination import LighthouseServer  # noqa: E402


class LighthouseActor:
    def __init__(self, min_replicas: int) -> None:
        self.server = LighthouseServer(
            bind="127.0.0.1:0", min_replicas=min_replicas, join_timeout_ms=500,
            quorum_tick_ms=50, heartbeat_timeout_ms=2000,
        )
        self.addr = f"127.0.0.1:{self.server.port}"

    def stop(self) -> None:
        self.server.shutdown()


@dataclass
class RetryPolicy:
    max_restarts: int = 3
    backoff_s: float = 1.0


class ReplicaActor:
    """Supervises one replica group's worker process (reference
    ReplicaActor + its restart loop)."""

    def __init__(self, rid: int, cmd: list, env: dict, policy: RetryPolicy) -> None:
        self.rid = rid
        self.cmd = cmd
        self.env = env
        self.policy = policy
        self.restarts = 0
        self.status = "pending"
        self._stop = threading.Event()
        self._thread = threading.Thread(target=self._run, daemon=True,
                                        name=f"replica_actor_{rid}")

    def start(self) -> None:
        self._thread.start()

    def _run(self) -> None:
        while not self._stop.is_set():
            self.status = "running"
            proc = subprocess.Popen(self.cmd, env=self.env)
            while proc.poll() is None:
                if self._stop.wait(0.5):
                    proc.terminate()
                    try:
                        proc.wait(timeout=30)
                    except subprocess.TimeoutExpired:
                        # SIGTERM-deaf worker: escalate so the actor thread
                        # reaches a terminal status and no orphan survives
                        proc.kill()
                        proc.wait()
                    self.status = "stopped"
                    return
            if proc.returncode == 0:
                self.status = "succeeded"
                return
            if self.restarts >= self.policy.max_restarts:
                self.status = "failed"
                print(f"[actor {self.rid}] out of restarts", flush=True)
                return
            self.restarts += 1
            self.status = "restarting"
            print(f"[actor {self.rid}] worker died rc={proc.returncode}; "
                  f"restart {self.restarts}/{self.policy.max_restarts}", flush=True)
            time.sleep(self.policy.backoff_s)

    def stop(self) -> None:
        self._stop.set()

    def join(self, timeout: float) -> None:
        self._thread.join(timeout)


class FailureController:
    """Injects failures through the lighthouse kill endpoint
    (reference FailureController)."""

    def __init__(self, lighthouse_addr: str, after_s: float) -> None:
        self._addr = lighthouse_addr
        self._after = after_s
        self._thread = threading.Thread(target=self._run, daemon=True)
        self.killed: list = []

    def start(self) -> None:
        self._thread.start()

    def _members(self) -> list:
        # one copy of the /status parsing: punisher.list_replicas handles
        # both participant shapes (bare ids vs member objects)
        from punisher import list_replicas

        members = list_replicas(self._addr)
        return sorted(set(members))

    def _run(self) -> None:
        time.sleep(self._after)
        try:
            members = []
            for _ in range(60):  # replicas may still be starting up
                members = self._members()
                if members:
                    break
                time.sleep(1)
            if not members:
                print("[chaos] no participants to kill", flush=True)
                return
            victim = members[-1]
            req = urllib.request.Request(
                f"http://{self._addr}/replica/{victim}/kill", method="POST"
            )
            with urllib.request.urlopen(req, timeout=10):
                pass
            self.killed.append(victim)
            print(f"[chaos] killed {victim}", flush=True)
        except Exception as e:  # noqa: BLE001
            print(f"[chaos] injection failed: {e}", flush=True)


@dataclass
class OrchestrationManager:
    """Wires the actors and owns the job lifecycle (reference
    OrchestrationManager)."""

    replicas: int
    steps: int
    policy: RetryPolicy = field(default_factory=RetryPolicy)
    inject_kill_after: float = 0.0

    def run(self) -> int:
        lighthouse = LighthouseActor(min_replicas=1)
        print(f"[orchestrator] lighthouse at http://{lighthouse.addr}/", flush=True)

        script = os.path.join(os.path.dirname(__file__), "train_ddp.py")
        actors = [
            ReplicaActor(
                rid,
                [sys.executable, script, "--steps", str(self.steps),
                 "--virtual-chips", "1"],
                dict(os.environ, TORCHFT_LIGHTHOUSE=lighthouse.addr,
                     REPLICA_GROUP_ID=str(rid)),
                self.policy,
            )
            for rid in range(self.replicas)
        ]
        chaos = None
        if self.inject_kill_after > 0:
            chaos = FailureController(lighthouse.addr, self.inject_kill_after)

        for a in actors:
            a.start()
        if chaos:
            chaos.start()

        deadline = time.monotonic() + 600
        while time.monotonic() < deadline:
            if all(a.status in ("succeeded", "failed", "stopped") for a in actors):
                break
            time.sleep(1)
        for a in actors:
            a.stop()
            a.join(timeout=30)
        lighthouse.stop()

        print("[orchestrator] summary:", flush=True)
        rc = 0
        for a in actors:
            print(f"  replica {a.rid}: {a.status} after {a.restarts} restart(s)",
                  flush=True)
            rc |= 0 if a.status == "succeeded" else 1
        if chaos and not chaos.killed:
            print("  (chaos injection did not fire)", flush=True)
        return rc


def main() -> None:
    parser = argparse.ArgumentParser(description=__doc__)
    parser.add_argument("--replicas", type=int, default=2)
    parser.add_argument("--steps", type=int, default=40)
    parser.add_argument("--max-restarts", type=int, default=3)
    parser.add_argument("--inject-kill-after", type=float, default=0.0)
    args = parser.parse_args()
    rc = OrchestrationManager(
        replicas=args.replicas,
        steps=args.steps,
        policy=RetryPolicy(max_restarts=args.max_restarts),
        inject_kill_after=args.inject_kill_after,
    ).run()
    sys.exit(rc)


if __name__ == "__main__":
    main()
